"""Fused detect megakernel (ops.score_fused) parity + quantized profiles.

The fused strategy must match the gather scorers in argmax everywhere and in
scores up to f32 reduction order (unquantized) or the documented quantized
tolerance class (int8/int16 tables, per-language f32 scales) — across dense
in-kernel-hash layouts, LUT membership, the exact12 short-gram split, window
limits, chunked long docs, and the degraded-mode ladder. Runs in Pallas
interpret mode on the CPU substrate (tests/conftest.py); the Mosaic lowering
is exercised by the opt-in real-TPU suite (test_tpu_hw).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from spark_languagedetector_tpu.api.runner import BatchRunner
from spark_languagedetector_tpu.models.profile import (
    GramProfile,
    dequantize_weights,
    quantize_weights,
)
from spark_languagedetector_tpu.ops import score as S
from spark_languagedetector_tpu.ops import score_fused as SF
from spark_languagedetector_tpu.ops.encoding import pad_batch
from spark_languagedetector_tpu.ops.vocab import (
    EXACT,
    HASHED,
    VocabSpec,
)
from spark_languagedetector_tpu.resilience.faults import FaultPlan
from spark_languagedetector_tpu.resilience import faults
from spark_languagedetector_tpu.resilience.policy import (
    CircuitBreaker,
    RetryPolicy,
)
from spark_languagedetector_tpu.telemetry import REGISTRY

RNG = np.random.default_rng(11)
L = 6


def _random_docs(n, lo=97, hi=112, max_len=60):
    docs = [
        bytes(RNG.integers(lo, hi, RNG.integers(0, max_len)).tolist())
        for _ in range(n)
    ]
    docs += [b"", b"a", b"ab", bytes(RNG.integers(0, 256, 200).tolist())]
    return docs


def _batch(docs, pad_to=256):
    b, l = pad_batch(docs, pad_to)
    return jnp.asarray(b), jnp.asarray(l)


def _dense_exact_bigram(n_learned=500):
    spec = VocabSpec(EXACT, (2,))
    w = np.zeros((spec.id_space_size, L), np.float32)
    learned = RNG.choice(spec.id_space_size, n_learned, replace=False)
    w[learned] = RNG.normal(size=(n_learned, L)).astype(np.float32)
    return spec, w


def _lut_fixture(spec, n_rows=200):
    V = spec.id_space_size
    lut = np.full(V, n_rows, np.int32)
    learned = RNG.choice(V, n_rows, replace=False)
    lut[learned] = np.arange(n_rows)
    w = np.zeros((n_rows + 1, L), np.float32)
    w[:-1] = RNG.normal(size=(n_rows, L)).astype(np.float32)
    return w, lut


def _dense_from_lut(spec, w, lut):
    miss = w.shape[0] - 1
    wd = np.zeros((spec.id_space_size, L), np.float32)
    ids = np.nonzero(lut != miss)[0]
    wd[ids] = w[lut[ids]]
    return wd


def _fused_scores(w, lut, spec, docs, quant=None, limit=None, pad_to=256):
    b, l = _batch(docs, pad_to)
    ft = SF.build_fused_tables(w, lut, spec, quant)
    return np.asarray(
        SF.score_batch_fused(
            b, l, jnp.asarray(ft.wq), jnp.asarray(ft.scales),
            None if ft.lut is None else jnp.asarray(ft.lut), limit,
            spec=spec, layout=ft.layout, block=128, interpret=True,
        )
    )


# ------------------------------------------------------ kernel parity -------
def test_fused_matches_gather_exact_dense():
    """Config-1 territory: exact bigram dense table, ids fully in-kernel."""
    spec, w = _dense_exact_bigram()
    docs = _random_docs(13)
    b, l = _batch(docs)
    ref = np.asarray(S.score_batch(b, l, jnp.asarray(w), None, spec=spec))
    got = _fused_scores(w, None, spec, docs)
    np.testing.assert_allclose(got, ref, atol=1e-3)
    assert (np.argmax(got, 1) == np.argmax(ref, 1)).all()


def test_fused_detect_variant_matches_scores_argmax():
    spec, w = _dense_exact_bigram()
    docs = _random_docs(11)
    b, l = _batch(docs)
    ft = SF.build_fused_tables(w, None, spec, None)
    scores = SF.score_batch_fused(
        b, l, jnp.asarray(ft.wq), jnp.asarray(ft.scales), None, None,
        spec=spec, layout=ft.layout, block=128, interpret=True,
    )
    labels, best = SF.detect_batch_fused(
        b, l, jnp.asarray(ft.wq), jnp.asarray(ft.scales), None, None,
        spec=spec, layout=ft.layout, block=128, interpret=True,
    )
    np.testing.assert_array_equal(
        np.asarray(labels), np.argmax(np.asarray(scores), axis=1)
    )
    np.testing.assert_allclose(
        np.asarray(best), np.max(np.asarray(scores), axis=1), atol=1e-5
    )


def test_fused_matches_gather_hashed_lut_fnv1a():
    """fnv1a-scheme hashed vocab: every length through XLA membership."""
    spec = VocabSpec(HASHED, (1, 2, 3), hash_bits=12)
    assert spec.hash_scheme == "fnv1a"
    w, lut = _lut_fixture(spec)
    docs = _random_docs(13)
    b, l = _batch(docs)
    ref = np.asarray(
        S.score_batch(b, l, jnp.asarray(w), jnp.asarray(lut), spec=spec)
    )
    got = _fused_scores(w, lut, spec, docs)
    np.testing.assert_allclose(got, ref, atol=1e-3)


def test_fused_matches_gather_hashed_dense_inkernel_fnv():
    """Dense fnv1a table: the FNV hash + power-of-two mask run in-kernel."""
    spec = VocabSpec(HASHED, (1, 2, 3), hash_bits=12)
    w, lut = _lut_fixture(spec)
    wd = _dense_from_lut(spec, w, lut)
    docs = _random_docs(13)
    b, l = _batch(docs)
    ref = np.asarray(S.score_batch(b, l, jnp.asarray(wd), None, spec=spec))
    ft = SF.build_fused_tables(wd, None, spec, None)
    assert ft.layout.rows_lengths == ()  # everything inline
    got = _fused_scores(wd, None, spec, docs)
    np.testing.assert_allclose(got, ref, atol=1e-3)


def test_fused_matches_gather_hashed_exact12_split():
    """exact12 LUT profile (the production 2^20 form at test scale): short
    grams score through the dense12 region with in-kernel polynomial ids,
    long grams through the re-based LUT rows plane."""
    spec = VocabSpec(HASHED, (1, 2, 3, 4, 5), hash_bits=17)
    assert spec.hash_scheme == "exact12"
    w, lut = _lut_fixture(spec, 300)
    docs = _random_docs(13)
    b, l = _batch(docs)
    ref = np.asarray(
        S.score_batch(b, l, jnp.asarray(w), jnp.asarray(lut), spec=spec)
    )
    ft = SF.build_fused_tables(w, lut, spec, None)
    assert [n for n, _, _, _ in ft.layout.inline] == [1, 2]
    assert ft.layout.rows_lengths == (3, 4, 5)
    got = _fused_scores(w, lut, spec, docs)
    np.testing.assert_allclose(got, ref, atol=1e-3)


def test_fused_matches_gather_hashed_exact12_dense_fold():
    """Dense exact12 table: the non-power-of-two fold modulus reduces
    in-kernel via the float-quotient trick — must match the host fold
    bit-for-bit (any mismatch re-buckets a window)."""
    spec = VocabSpec(HASHED, (1, 2, 3, 4, 5), hash_bits=17)
    w, lut = _lut_fixture(spec, 300)
    wd = _dense_from_lut(spec, w, lut)
    docs = _random_docs(13)
    b, l = _batch(docs)
    ref = np.asarray(S.score_batch(b, l, jnp.asarray(wd), None, spec=spec))
    got = _fused_scores(wd, None, spec, docs)
    np.testing.assert_allclose(got, ref, atol=1e-3)


def test_fused_respects_window_limit():
    spec = VocabSpec(HASHED, (1, 2, 3), hash_bits=12)
    w, lut = _lut_fixture(spec)
    docs = _random_docs(9)
    b, l = _batch(docs)
    limit = jnp.asarray(RNG.integers(1, 40, len(docs)).astype(np.int32))
    ref = np.asarray(
        S.score_batch(
            b, l, jnp.asarray(w), jnp.asarray(lut), spec=spec,
            window_limit=limit,
        )
    )
    got = _fused_scores(w, lut, spec, docs, limit=limit)
    np.testing.assert_allclose(got, ref, atol=1e-3)


def test_fused_empty_and_all_miss_docs_argmax_zero():
    """Reference Q6 semantics: empty docs and docs hitting no learned gram
    score all-zeros and argmax to index 0."""
    spec, w = _dense_exact_bigram(n_learned=0)  # nothing learned
    docs = [b"", b"anything", bytes(range(200, 240))]
    b, l = _batch(docs)
    ft = SF.build_fused_tables(w, None, spec, "int8")
    labels, best = SF.detect_batch_fused(
        b, l, jnp.asarray(ft.wq), jnp.asarray(ft.scales), None, None,
        spec=spec, layout=ft.layout, block=128, interpret=True,
    )
    np.testing.assert_array_equal(np.asarray(labels), 0)
    np.testing.assert_array_equal(np.asarray(best), 0.0)


# ------------------------------------------------------ quantization --------
def test_quantize_weights_round_trip_fixed_point():
    w = RNG.normal(size=(300, L)).astype(np.float32) * 7.5
    for dtype, itemsize in (("int8", 1), ("int16", 2)):
        q, scales = quantize_weights(w, dtype)
        assert q.dtype == np.dtype(dtype) and scales.dtype == np.float32
        deq = dequantize_weights(q, scales)
        q2, scales2 = quantize_weights(deq, dtype)
        np.testing.assert_array_equal(q, q2)  # fixed point
        np.testing.assert_array_equal(scales, scales2)
        assert q.nbytes == w.shape[0] * L * itemsize


def test_quantize_weights_zero_column_and_bad_dtype():
    w = np.zeros((10, 3), np.float32)
    q, scales = quantize_weights(w, "int8")
    np.testing.assert_array_equal(scales, 1.0)
    np.testing.assert_array_equal(dequantize_weights(q, scales), 0.0)
    with pytest.raises(ValueError, match="unknown quantization"):
        quantize_weights(w, "int4")


@pytest.mark.parametrize("quant", ["int8", "int16"])
def test_fused_quantized_agreement_and_table_bytes(quant):
    spec = VocabSpec(HASHED, (1, 2, 3, 4, 5), hash_bits=17)
    w, lut = _lut_fixture(spec, 300)
    docs = _random_docs(13)
    b, l = _batch(docs)
    ref = np.asarray(
        S.score_batch(b, l, jnp.asarray(w), jnp.asarray(lut), spec=spec)
    )
    ft = SF.build_fused_tables(w, lut, spec, quant)
    ratio = {"int8": 0.25, "int16": 0.5}[quant]
    assert ft.table_bytes == int(ft.f32_bytes * ratio)
    got = _fused_scores(w, lut, spec, docs, quant=quant)
    agree = (np.argmax(got, 1) == np.argmax(ref, 1)).mean()
    assert agree == 1.0  # test fixture is small; errors are ~1e-2 relative


# ------------------------------------------------------ runner integration --
def test_runner_fused_strategy_matches_gather_with_chunking():
    """End-to-end through BatchRunner incl. an oversized doc split into
    chunks whose scaled scores must sum exactly across dispatches."""
    spec, w = _dense_exact_bigram()
    docs = _random_docs(11) + [bytes(b"abcde" * 300)]  # forces chunking
    ref = BatchRunner(
        weights=jnp.asarray(w), lut=None, spec=spec,
        strategy="gather", length_buckets=(128, 256),
    ).score(docs)
    r = BatchRunner(
        weights=jnp.asarray(w), lut=None, spec=spec,
        strategy="fused", length_buckets=(128, 256),
    )
    got = r.score(docs)
    np.testing.assert_allclose(got, ref, atol=1e-3)
    assert r.table_bytes() == spec.id_space_size * L * 4


def test_runner_fused_quantized_chunked_labels_match_f32():
    spec, w = _dense_exact_bigram()
    docs = _random_docs(9) + [bytes(b"lmnop" * 300)]
    kw = dict(
        weights=jnp.asarray(w), lut=None, spec=spec, strategy="fused",
        length_buckets=(128, 256),
    )
    f32_ids = BatchRunner(**kw).predict_ids(docs)
    q_ids = BatchRunner(**kw, quantization="int8").predict_ids(docs)
    np.testing.assert_array_equal(q_ids, f32_ids)


def test_runner_fused_hashed_lut_profile():
    spec = VocabSpec(HASHED, (1, 2, 3, 4, 5), hash_bits=17)
    w, lut = _lut_fixture(spec, 300)
    docs = _random_docs(11)
    kw = dict(
        weights=jnp.asarray(w), lut=jnp.asarray(lut), spec=spec,
        length_buckets=(128, 256),
    )
    ref = BatchRunner(**kw, strategy="gather").score(docs)
    got = BatchRunner(**kw, strategy="fused").score(docs)
    np.testing.assert_allclose(got, ref, atol=1e-3)


def test_runner_quantization_forces_fused_under_auto():
    spec, w = _dense_exact_bigram()
    r = BatchRunner(
        weights=jnp.asarray(w), lut=None, spec=spec, quantization="int16",
    )
    assert r.strategy == "fused"
    assert "quantization" in r.strategy_reason


def test_runner_quantization_rejects_other_strategies():
    spec, w = _dense_exact_bigram()
    with pytest.raises(ValueError, match="fused strategy only"):
        BatchRunner(
            weights=jnp.asarray(w), lut=None, spec=spec,
            strategy="gather", quantization="int8",
        )


def test_runner_fused_rejects_cuckoo_membership():
    from spark_languagedetector_tpu.ops.cuckoo import build_cuckoo
    from spark_languagedetector_tpu.ops.vocab import gram_key

    spec = VocabSpec(EXACT, (1, 2, 3, 4, 5))
    grams = sorted(
        {bytes(RNG.integers(97, 110, 4).tolist()) for _ in range(100)}
    )
    w = np.zeros((len(grams) + 1, L), np.float32)
    keys = [gram_key(g) for g in grams]
    table = build_cuckoo(
        np.asarray([k[0] for k in keys], np.int32),
        np.asarray([k[1] for k in keys], np.int32),
    )
    with pytest.raises(ValueError, match="fused"):
        BatchRunner(
            weights=jnp.asarray(w), lut=None, spec=spec, cuckoo=table,
            strategy="fused",
        )


def test_auto_select_reasons_per_platform():
    """The auto branch logs WHY a deployment landed on a strategy; the
    decision table is pinned here platform-by-platform."""
    spec, w = _dense_exact_bigram()
    r = BatchRunner(weights=jnp.asarray(w), lut=None, spec=spec)
    # CPU substrate: XLA one-hot, never interpret-mode pallas.
    assert r.strategy == "onehot" and "one-hot" in r.strategy_reason
    # Simulated TPU: fused preferred wherever it covers the form.
    strat, reason = BatchRunner._auto_select(r, "tpu", True, True, True)
    assert strat == "fused" and "fused" in reason
    strat, reason = BatchRunner._auto_select(r, "tpu", False, True, False)
    assert strat == "pallas"
    strat, reason = BatchRunner._auto_select(r, "tpu", False, False, True)
    assert strat == "hybrid"


def test_score_span_carries_strategy_reason():
    spec, w = _dense_exact_bigram()
    events = []
    sink = type("S", (), {"emit": lambda self, ev: events.append(ev)})()
    REGISTRY.add_sink(sink)
    try:
        BatchRunner(
            weights=jnp.asarray(w), lut=None, spec=spec,
            length_buckets=(128,),
        ).score([b"abc"])
    finally:
        REGISTRY.remove_sink(sink)
    score_spans = [
        ev for ev in events
        if ev.get("event") == "telemetry.span" and ev.get("path") == "score"
    ]
    assert score_spans and score_spans[0]["strategy_reason"]


# ------------------------------------------------------ degraded ladder -----
def test_runner_fused_degraded_ladder_fused_gather_host():
    """The fused strategy sits at the top of the degradation ladder: with
    the fused dispatch AND the device-gather rung both failing, the host
    rung carries the batch — bit-identical to the gather oracle (degraded
    results never carry quantization error: the ladder reads the original
    f32 tables)."""
    spec, w = _dense_exact_bigram()
    docs = _random_docs(8)[:8]
    oracle = BatchRunner(
        weights=jnp.asarray(w), lut=None, spec=spec,
        batch_size=8, strategy="gather", length_buckets=(128, 256),
    ).score(docs)

    clk = {"t": 0.0}
    runner = BatchRunner(
        weights=jnp.asarray(w), lut=None, spec=spec,
        batch_size=8, strategy="fused", quantization="int8",
        length_buckets=(128, 256),
        retry_policy=RetryPolicy(max_attempts=1, base_delay_s=0.0),
        breaker=CircuitBreaker(
            failure_threshold=1, cooldown_s=1e9, clock=lambda: clk["t"]
        ),
    )
    # Fail the fused dispatch AND the ladder's device-gather rung (both
    # count at score/dispatch): the host rung must carry the batch.
    with faults.plan_scope(FaultPlan.parse("score/dispatch:error@1-2")):
        got = runner.score(docs)
    np.testing.assert_allclose(got, np.asarray(oracle), rtol=1e-5)
    snap = REGISTRY.snapshot()
    assert snap["counters"].get("resilience/degraded_host", 0) >= 1


def test_runner_fused_degraded_gather_rung_exact():
    """One injected fused failure with retries exhausted rides the
    device-gather rung (not host) and stays exact."""
    spec, w = _dense_exact_bigram()
    docs = _random_docs(6)[:6]
    oracle = BatchRunner(
        weights=jnp.asarray(w), lut=None, spec=spec,
        batch_size=8, strategy="gather", length_buckets=(128, 256),
    ).score(docs)
    before = REGISTRY.snapshot()["counters"].get(
        "resilience/degraded_gather", 0
    )
    runner = BatchRunner(
        weights=jnp.asarray(w), lut=None, spec=spec,
        batch_size=8, strategy="fused", length_buckets=(128, 256),
        retry_policy=RetryPolicy(max_attempts=1, base_delay_s=0.0),
    )
    with faults.plan_scope(FaultPlan.parse("score/dispatch:error@1")):
        got = runner.score(docs)
    np.testing.assert_allclose(got, np.asarray(oracle), rtol=1e-5)
    after = REGISTRY.snapshot()["counters"].get(
        "resilience/degraded_gather", 0
    )
    assert after == before + 1


# ------------------------------------------------------ persist round trip --
def test_quantized_persist_round_trip_scores_identical(tmp_path):
    """save(quantized) → load → fused-quantized scores are bit-identical
    to the pre-save model's (requantization is a fixed point), and the
    loaded profile's f32 weights are exactly q * scale."""
    from spark_languagedetector_tpu import LanguageDetector, Table
    from spark_languagedetector_tpu.models.estimator import (
        LanguageDetectorModel,
    )

    langs = ["en", "de", "fr"]
    docs = ["the fox jumps", "der fuchs springt", "le renard saute"] * 10
    labels = ["en", "de", "fr"] * 10
    model = LanguageDetector(langs, [1, 2], 120).fit(
        Table({"lang": labels, "fulltext": docs})
    )
    model.set_quantization("int8")
    path = str(tmp_path / "m")
    model.write().overwrite().quantized("int8").save(path)
    loaded = LanguageDetectorModel.load(path)
    assert loaded.get_or_default("quantization") == "int8"

    q, scales = quantize_weights(model.profile.weights, "int8")
    np.testing.assert_array_equal(
        np.asarray(loaded.profile.weights, np.float32),
        dequantize_weights(q, scales),
    )
    probe = [b"the quick fox", b"der schnelle fuchs", b"le renard rapide"]
    np.testing.assert_array_equal(
        model._get_runner().score(probe), loaded._get_runner().score(probe)
    )


def test_quantized_persist_rejects_reference_layout(tmp_path):
    from spark_languagedetector_tpu.persist.io import save_model

    profile = GramProfile.from_gram_map(
        {b"ab": [0.5, 0.2]}, ("en", "de"), (2,)
    )
    with pytest.raises(ValueError, match="native-layout"):
        save_model(
            tmp_path / "m", profile, "uid", {}, layout="reference",
            quantize="int8",
        )


# ------------------------------------------------------ serving hot-swap ----
def test_registry_hot_swap_quantized_profile():
    """A quantized model swaps into the serving registry like any other
    version: parity against the f32 version's labels on the probe docs,
    quantization surfaced in describe() (the /varz payload)."""
    from spark_languagedetector_tpu import LanguageDetector, Table
    from spark_languagedetector_tpu.serve.registry import ModelRegistry

    langs = ["en", "de"]
    docs = ["the quick brown fox", "der schnelle braune fuchs"] * 10
    labels = ["en", "de"] * 10
    model = LanguageDetector(langs, [1, 2], 120).fit(
        Table({"lang": labels, "fulltext": docs})
    )
    reg = ModelRegistry(prewarm_docs=(b"warm up doc",))
    v1 = reg.install(model)
    qmodel = model.copy()
    qmodel.set_quantization("int16")
    v2 = reg.install(qmodel)
    assert reg.current_version() == v2
    versions = {v["version"]: v for v in reg.versions()}
    assert versions[v1]["quantization"] is None
    assert versions[v2]["quantization"] == "int16"
    assert versions[v2]["strategy"] == "fused"
    probe = [b"the brown fox jumps", b"der braune fuchs springt"]
    with reg.lease() as entry:
        got = entry.runner.predict_ids(probe)
    want = model._get_runner().predict_ids(probe)
    np.testing.assert_array_equal(got, want)
