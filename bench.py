"""Benchmark: langid docs/sec/chip vs a per-row CPU scoring baseline.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "docs/sec", "vs_baseline": N}

Config (BASELINE.md config 1 by default): bigram+trigram byte model over a
synthetic multi-language Wikipedia-like corpus; baseline = the reference's
per-row scoring semantics (per-window dict lookup + vector accumulate,
LanguageDetectorModel.scala:139-152) reimplemented in Python, measured on
this host's CPU; TPU number = the framework's micro-batched device scorer.

The baseline is *measured, not cited* (BASELINE.md). Accuracy parity is a
hard gate: if device argmax labels disagree with the baseline on the
comparison subset, the script exits nonzero instead of reporting perf.

Environment knobs:
    BENCH_CONFIG       1 (default) | 3 | 5  — which BASELINE config shape
    BENCH_DOCS         number of docs to score (default 20000)
    BENCH_BASELINE_DOCS  docs for the CPU baseline timing (default 1000)
"""

from __future__ import annotations

import json
import os
import time

import numpy as np


# ---------------------------------------------------------------- corpus ----
_LANG_CHARS = {
    "en": "the quick brown fox jumps over lazy dog and that is very nice ",
    "de": "der schnelle braune fuchs springt über den faulen hund schön ",
    "fr": "le renard brun rapide saute par dessus chien paresseux très ",
    "es": "el zorro marrón rápido salta sobre perro perezoso muy bien ",
    "it": "la volpe marrone veloce salta sopra il cane pigro molto bene ",
    "nl": "de snelle bruine vos springt over de luie hond erg mooi ",
    "pt": "a raposa marrom rápida pula sobre o cão preguiçoso muito bom ",
    "sv": "den snabba bruna räven hoppar över den lata hunden mycket fin ",
    "pl": "szybki brązowy lis przeskakuje nad leniwym psem bardzo ładnie ",
    "fi": "nopea ruskea kettu hyppää laiskan koiran yli erittäin mukava ",
}


def make_corpus(langs, n_docs, mean_len=1500, seed=0):
    """Synthetic Wikipedia-like docs: ~mean_len bytes of language-typical words."""
    rng = np.random.default_rng(seed)
    docs, labels = [], []
    word_lists = {l: _LANG_CHARS[l].split() for l in langs}
    for i in range(n_docs):
        lang = langs[i % len(langs)]
        words = word_lists[lang]
        target = max(30, int(rng.normal(mean_len, mean_len / 4)))
        n_words = max(4, target // 7)
        text = " ".join(rng.choice(words, size=n_words))
        docs.append(text)
        labels.append(lang)
    return docs, labels


# ------------------------------------------------- reference CPU baseline ----
def baseline_score(text: str, gram_map: dict, num_langs: int, gram_lengths):
    """Reference hot-loop semantics: per-window map lookup + accumulate."""
    data = text.encode("utf-8")
    acc = [0.0] * num_langs
    for n in gram_lengths:
        if len(data) >= n:
            for i in range(len(data) - n + 1):
                vec = gram_map.get(data[i : i + n])
                if vec is not None:
                    for j in range(num_langs):
                        acc[j] += vec[j]
        elif data:
            vec = gram_map.get(data)
            if vec is not None:
                for j in range(num_langs):
                    acc[j] += vec[j]
    return acc


def main():
    config = int(os.environ.get("BENCH_CONFIG", "1"))
    n_docs = int(os.environ.get("BENCH_DOCS", "20000"))
    n_baseline = int(os.environ.get("BENCH_BASELINE_DOCS", "1000"))

    if config == 1:
        langs, gram_lengths, k, vocab_mode, bits = (
            ["en", "de", "fr"], [2], 2000, "exact", 20)
        label = "config1 bigram en/de/fr"
    elif config == 3:
        langs, gram_lengths, k, vocab_mode, bits = (
            list(_LANG_CHARS), [1, 2, 3], 3000, "exact", 20)
        label = "config3-ish n=1..3, 10 languages"
    else:
        langs, gram_lengths, k, vocab_mode, bits = (
            list(_LANG_CHARS), [1, 2, 3, 4, 5], 3000, "hashed", 20)
        label = "config5-ish n=1..5 hashed 2^20"

    from spark_languagedetector_tpu import LanguageDetector, Table

    train_docs, train_labels = make_corpus(langs, 60 * len(langs), seed=1)
    detector = LanguageDetector(langs, gram_lengths, k).set_vocab_mode(
        vocab_mode
    ).set_hash_bits(bits)
    model = detector.fit(Table({"lang": train_labels, "fulltext": train_docs}))

    eval_docs, _ = make_corpus(langs, n_docs, seed=2)
    eval_bytes_total = sum(len(d.encode()) for d in eval_docs)

    # --- CPU baseline (reference per-row semantics), measured --------------
    gram_map = (
        {g: list(v) for g, v in model.gram_probabilities.items()}
        if vocab_mode == "exact"
        else None
    )
    sub = eval_docs[:n_baseline]
    if gram_map is not None:
        t0 = time.perf_counter()
        base_scores = [baseline_score(t, gram_map, len(langs), gram_lengths) for t in sub]
        t_base = time.perf_counter() - t0
    else:
        # Hashed mode has no byte-keyed map; baseline uses bucket dict.
        compact = model.profile.compacted()
        bucket_map = {
            int(b): compact.weights[r].tolist()
            for r, b in enumerate(compact.ids)
        }
        spec = model.profile.spec
        t0 = time.perf_counter()
        base_scores = []
        for text in sub:
            data = text.encode("utf-8")
            acc = [0.0] * len(langs)
            for n in gram_lengths:
                for i in range(max(len(data) - n + 1, 0)):
                    vec = bucket_map.get(spec.gram_to_id(data[i : i + n]))
                    if vec is not None:
                        for j in range(len(langs)):
                            acc[j] += vec[j]
            base_scores.append(acc)
        t_base = time.perf_counter() - t0
    baseline_dps = len(sub) / t_base

    # Honest-baseline column: the per-row loop above mirrors the reference's
    # *semantics* (JVM map lookup + axpy) but Python-per-row is far slower
    # than the JVM; a vectorized-numpy host scorer is the strongest CPU
    # implementation this repo ships, so report it alongside to keep
    # vs_baseline from reading as a vs-JVM claim.
    from spark_languagedetector_tpu.ops.score import score_batch_numpy

    cw, cids = model.profile.host_arrays()
    t0 = time.perf_counter()
    score_batch_numpy(
        [t.encode("utf-8") for t in sub], cw, cids, model.profile.spec
    )
    baseline_numpy_dps = len(sub) / (time.perf_counter() - t0)

    # --- framework scorer on the accelerator -------------------------------
    from spark_languagedetector_tpu.ops.encoding import texts_to_bytes

    runner = model._get_runner()
    docs_b = texts_to_bytes(eval_docs)
    # Warmup = one full pass, so every (batch, length-bucket) shape XLA will
    # see — including the ragged final batch — is compiled outside the timed
    # window.
    scores = runner.score(docs_b)
    # Best of 3 timed passes: the device link (e.g. a tunneled TPU) has
    # bursty latency that can dominate a single pass; the best pass is the
    # closest observable to steady-state throughput. The median is reported
    # alongside so the burst variance is visible in the artifact.
    pass_times = []
    for _ in range(3):
        t0 = time.perf_counter()
        scores = runner.score(docs_b)
        pass_times.append(time.perf_counter() - t0)
    t_dev = min(pass_times)
    device_dps = n_docs / t_dev
    median_dps = n_docs / sorted(pass_times)[len(pass_times) // 2]

    # --- accuracy parity (hard gate: a broken scorer must not print a
    # plausible speedup) -----------------------------------------------------
    base_pred = [int(np.argmax(s)) for s in base_scores]
    dev_pred = np.argmax(scores[: len(sub)], axis=1).tolist()
    parity = float(np.mean([a == b for a, b in zip(base_pred, dev_pred)]))
    if parity < 1.0:
        raise SystemExit(
            f"accuracy parity violated: {parity:.4f} — device argmax disagrees "
            f"with the reference-semantics baseline; refusing to report perf"
        )

    import jax

    result = {
        "metric": f"langid docs/sec/chip ({label}, {jax.default_backend()})",
        "value": round(device_dps, 1),
        "unit": "docs/sec",
        "vs_baseline": round(device_dps / baseline_dps, 2),
        "median_docs_per_s": round(median_dps, 1),
        "baseline_docs_per_s": round(baseline_dps, 1),
        "baseline_kind": "python-per-row (reference hot-loop semantics)",
        "baseline_numpy_docs_per_s": round(baseline_numpy_dps, 1),
        "argmax_parity": parity,
        "eval_docs": n_docs,
        "eval_mb": round(eval_bytes_total / 1e6, 1),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
