"""Benchmark: langid docs/sec/chip vs a per-row CPU scoring baseline.

Covers all five BASELINE.md configs in one invocation, printing ONE JSON
line per config (the headline north-star config 1 is printed LAST):

  1. bigram (n=2) byte model, 3 languages (en/de/fr)           — exact
  2. n=1..3 mixed-gram model, 10 European languages            — exact
  3. n=1..5, 50-language profile matrix (CLD2-scale)           — exact (cuckoo)
  4. streaming micro-batch langid (run_stream + memory source) — config-2 model
  5. 176-language fastText-lid parity, n=1..5 hashed 2^20      — hashed exact12

Corpora are synthetic Wikipedia-like documents (~1.5KB each): the first ten
languages use real word lists, the rest procedurally generated per-language
vocabularies (distinct letter subsets + word shapes). BASELINE names
Wikipedia/CommonCrawl dumps; none are available in this zero-egress image,
so the baseline is *measured, not cited* (BASELINE.md) on the same synthetic
corpus for both sides.

Four baseline denominators per config, reported side by side:
  * ``vs_cpp`` / ``baseline_cpp_docs_per_s`` — a compiled per-row scorer
    with the reference hot loop's exact shape (native/refscorer.cpp:
    hash-map probe per window + double axpy + argmax, -O3, one thread).
    Stronger than the reference's JVM loop (no per-window allocation), so
    this is the LOWER bound on the true vs-Scala-UDF multiple; for exact
    configs its labels must agree with the per-row Python baseline
    exactly (``cpp_agreement``, enforced).
  * ``vs_cpp_mt`` / ``baseline_cpp_mt_docs_per_s`` — the same compiled
    scorer with ``os.cpu_count()`` threads: one TPU chip vs one whole
    multi-core host (the reference's transform is cluster-parallel by
    contract, so the single-thread number stands in for one executor core
    and this one for a whole executor host).
  * ``vs_baseline`` / ``baseline_docs_per_s`` — the same per-row
    semantics (per-window dict lookup + vector accumulate,
    LanguageDetectorModel.scala:139-152) in pure Python. Far slower than
    any JVM — the UPPER bound on the vs-Scala-UDF multiple.
  * ``vs_numpy`` / ``baseline_numpy_docs_per_s`` — the strongest
    vectorized CPU implementation this repo ships (numpy host scorer).

Each line also carries ``compute_docs_per_s``: device throughput with
operands already resident (no host->device wire), so kernel progress stays
visible when the tunnel's bandwidth — which bounds end-to-end ``value`` —
varies (the wire is a relay here, ~30-90MB/s bursty).

Accuracy parity is a hard gate per config: if device argmax labels disagree
with the per-row baseline on the comparison subset (>= 1000 docs or the
whole eval set), the script exits nonzero instead of reporting perf.

Environment knobs:
    BENCH_CONFIGS        comma list, default "2,3,4,5,1" (1 last = headline)
    BENCH_DOCS           override eval-doc count for every config
    BENCH_BASELINE_DOCS  override baseline/parity-doc count for every config
    BENCH_SOFT_BUDGET_S  soft wall-clock budget (default 1500): once spent,
                         intermediate configs are skipped (noted on stderr)
                         so the final/headline config always runs; the
                         additive legs (accuracy legs, hashed-vs-exact,
                         fit bench) skip first, when under ~2-4 min remain
    SLD_TPU_TESTS        "1" => also run the real-TPU parity suite
                         (tests/test_tpu_hw.py) after the headline config,
                         reporting to stderr (stdout stays parseable)
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

# ---------------------------------------------------------------- corpus ----
# HARD corpus (round 5): language-FAMILY structure with Zipf-weighted shared
# vocabulary. Earlier rounds' per-language disjoint vocabularies separated so
# cleanly that every accuracy leg read 1.0 on every config and could not
# detect a regression (VERDICT r4). Here sibling languages share (a) one
# family alphabet, (b) a set of family "function words" occupying the TOP
# Zipf ranks (~identical across siblings, like es/pt 'de'/'la'/'em'), and
# (c) mutated forms of common family root stems; cross-family "loanwords"
# (internet/hotel/taxi...) appear in every language. Word frequencies are
# Zipf-distributed, so a short document can easily contain only shared
# words — exactly the regime where real langid systems err. Legs are tuned
# so the REFERENCE SEMANTICS ITSELF scores ~0.7-0.97 on the hard legs
# (reported per leg as *_ref via the per-row baseline) — deltas are visible.
_LANG_CHARS = {
    "en": "the quick brown fox jumps over lazy dog and that is very nice ",
    "de": "der schnelle braune fuchs springt über den faulen hund schön ",
    "fr": "le renard brun rapide saute par dessus chien paresseux très ",
    "es": "el zorro marrón rápido salta sobre perro perezoso muy bien ",
    "it": "la volpe marrone veloce salta sopra il cane pigro molto bene ",
    "nl": "de snelle bruine vos springt over de luie hond erg mooi ",
    "pt": "a raposa marrom rápida pula sobre o cão preguiçoso muito bom ",
    "sv": "den snabba bruna räven hoppar över den lata hunden mycket fin ",
    "pl": "szybki brązowy lis przeskakuje nad leniwym psem bardzo ładnie ",
    "fi": "nopea ruskea kettu hyppää laiskan koiran yli erittäin mukava ",
}
_ALPHABET = "abcdefghijklmnopqrstuvwxyzäöüßéèêñçåøæšžčłćİığj"

# Cross-family loanwords: present in EVERY language's vocabulary (mid Zipf
# ranks) — globally uninformative tokens, like real international vocabulary.
_LOANWORDS = [
    "internet", "hotel", "taxi", "radio", "metro",
    "video", "pizza", "banana", "foto", "bank",
]

# Real-language family assignment (Romance / Germanic / the rest); synthetic
# languages l010+ are grouped into families of four siblings each.
_REAL_FAMILY = {
    "fr": "romance", "es": "romance", "it": "romance", "pt": "romance",
    "en": "germanic", "de": "germanic", "nl": "germanic", "sv": "germanic",
    "pl": "balto", "fi": "balto",
}


def language_names(n: int) -> list[str]:
    """First ten real languages, then procedurally named synthetic ones."""
    real = list(_LANG_CHARS)
    return real[:n] if n <= len(real) else real + [
        f"l{i:03d}" for i in range(len(real), n)
    ]


def family_of(lang: str) -> str:
    if lang in _REAL_FAMILY:
        return _REAL_FAMILY[lang]
    return f"syn{(int(lang[1:]) - 10) // 4}"


def _rng_of(tag: str) -> np.random.Generator:
    # zlib.crc32 is stable across processes (hash() is salted per run, which
    # would make the synthetic corpora — and the bench numbers — drift).
    import zlib

    return np.random.default_rng(zlib.crc32(tag.encode()))


def _gen_word(rng, letters, lo: int, hi: int) -> str:
    return "".join(rng.choice(letters, size=int(rng.integers(lo, hi))))


def _family_alphabet(fam: str) -> list[str]:
    """One 15-letter alphabet per FAMILY (siblings share it, so unigram
    statistics no longer separate them — higher-order grams must)."""
    return list(_rng_of("alpha:" + fam).choice(
        list(_ALPHABET), size=15, replace=False
    ))


def _family_shared(fam: str) -> list[str]:
    """12 short family 'function words', identical across siblings, holding
    the top Zipf ranks."""
    rng = _rng_of("shared:" + fam)
    letters = _family_alphabet(fam)
    return list(dict.fromkeys(
        _gen_word(rng, letters, 2, 5) for _ in range(18)
    ))[:12]


def _family_roots(fam: str) -> list[str]:
    """30 family root stems that siblings mutate into their own forms."""
    rng = _rng_of("roots:" + fam)
    letters = _family_alphabet(fam)
    return list(dict.fromkeys(
        _gen_word(rng, letters, 4, 9) for _ in range(40)
    ))[:30]


_word_cache: dict[str, list[str]] = {}


def word_list(lang: str) -> list[str]:
    """Ranked word inventory (most frequent first) for a language:
    family-shared function words at the top ranks, loanwords at mid ranks,
    then per-language material — mutated family roots (shared stem,
    language-specific mutation/suffix) interleaved with unique words (the
    real-language word lists where available, procedural otherwise)."""
    cached = _word_cache.get(lang)
    if cached is not None:
        return cached
    fam = family_of(lang)
    rng = _rng_of("lang:" + lang)
    letters = _family_alphabet(fam)
    suffix = _gen_word(rng, letters, 1, 3)
    roots = _family_roots(fam)
    mutated = []
    for i in rng.choice(len(roots), size=20, replace=False):
        w = roots[int(i)]
        if rng.random() < 0.5:  # single-letter shift, orthography-style
            j = int(rng.integers(0, len(w)))
            w = w[:j] + str(rng.choice(letters)) + w[j + 1:]
        if rng.random() < 0.6:
            w = w + suffix
        mutated.append(w)
    unique = _LANG_CHARS[lang].split() if lang in _LANG_CHARS else []
    while len(unique) < 26:
        unique.append(_gen_word(rng, letters, 3, 9))
    tail: list[str] = []
    for m, u in zip(mutated, unique):
        tail.extend((m, u))
    tail.extend(mutated[len(unique):] + unique[len(mutated):])
    ranked = _family_shared(fam) + _LOANWORDS + tail
    out = list(dict.fromkeys(ranked))
    _word_cache[lang] = out
    return out


def _zipf_probs(n: int, s: float = 1.05) -> np.ndarray:
    w = 1.0 / np.power(np.arange(1, n + 1) + 2.0, s)
    return w / w.sum()


_zipf_cache: dict[int, np.ndarray] = {}


def _zipf(n: int) -> np.ndarray:
    p = _zipf_cache.get(n)
    if p is None:
        p = _zipf_cache[n] = _zipf_probs(n)
    return p


def make_corpus(langs, n_docs, mean_len=1500, seed=0, len_range=None):
    """Synthetic Wikipedia-like docs: Zipf-weighted draws from each
    language's ranked vocabulary. ``len_range=(lo, hi)`` switches to uniform
    doc lengths in bytes (the hard short-doc legs use (20, 120))."""
    rng = np.random.default_rng(seed)
    words = {l: np.asarray(word_list(l)) for l in langs}
    probs = {l: _zipf(len(words[l])) for l in langs}
    docs, labels = [], []
    for i in range(n_docs):
        lang = langs[i % len(langs)]
        if len_range is not None:
            target = int(rng.integers(len_range[0], len_range[1] + 1))
        else:
            target = max(30, int(rng.normal(mean_len, mean_len / 4)))
        n_words = max(3, target // 7)
        docs.append(
            " ".join(rng.choice(words[lang], size=n_words, p=probs[lang]))
        )
        labels.append(lang)
    return docs, labels


def make_mixed_corpus(lang_a, lang_b, n_docs, mean_len=400, frac_a=0.7, seed=11):
    """Code-switched docs: ``frac_a`` of the words from lang_a, the rest from
    lang_b, both Zipf-weighted. Ground truth = the dominant language."""
    rng = np.random.default_rng(seed)
    wa, wb = np.asarray(word_list(lang_a)), np.asarray(word_list(lang_b))
    pa, pb = _zipf(len(wa)), _zipf(len(wb))
    docs = []
    for _ in range(n_docs):
        n_words = max(6, int(rng.normal(mean_len, mean_len / 5)) // 7)
        mask = rng.random(n_words) < frac_a
        picks = np.where(
            mask,
            rng.choice(wa, n_words, p=pa),
            rng.choice(wb, n_words, p=pb),
        )
        docs.append(" ".join(picks))
    return docs


def make_codeswitch_corpus(
    langs, n_docs, block_bytes=1280, blocks=(2, 3), seed=23
):
    """Block-structured code-switched docs with KNOWN span boundaries —
    the segmentation bench corpus (docs/SEGMENTATION.md). Each document
    concatenates 2-3 single-language blocks of ~``block_bytes`` bytes
    (adjacent blocks always differ in language), and the ground truth is
    returned as byte-offset spans ``[(start, end, lang), ...]`` exactly
    partitioning the document. Unlike :func:`make_mixed_corpus` (word-
    level interleave — no contiguous truth spans exist), this corpus has
    an objectively correct segmentation to score span F1 against."""
    rng = np.random.default_rng(seed)
    words = {l: np.asarray(word_list(l)) for l in langs}
    probs = {l: _zipf(len(words[l])) for l in langs}

    def block(lang, target):
        out = []
        size = -1  # first word adds no separator
        while size < target:
            w = str(rng.choice(words[lang], p=probs[lang]))
            out.append(w)
            size += len(w.encode("utf-8")) + 1
        return " ".join(out)

    docs, truth = [], []
    for i in range(n_docs):
        n_blocks = int(rng.choice(list(blocks)))
        seq = []
        prev = None
        for _ in range(n_blocks):
            pick = [l for l in langs if l != prev]
            lang = str(rng.choice(pick))
            seq.append(lang)
            prev = lang
        parts = [block(l, block_bytes) for l in seq]
        spans = []
        pos = 0
        for lang, part in zip(seq, parts):
            nb = len(part.encode("utf-8"))
            # The joining space after a block belongs to that block —
            # one boundary byte, noise at the F1 level.
            end = pos + nb + 1
            spans.append([pos, end, lang])
            pos = end
        spans[-1][1] = pos - 1  # no trailing separator on the last block
        docs.append(" ".join(parts))
        truth.append([tuple(s) for s in spans])
    return docs, truth


def span_byte_f1(truth_spans, pred_spans, doc_len: int) -> dict:
    """Byte-level segmentation quality of ONE document: per-language
    true/false positives/negatives of the byte labeling the two span
    lists induce. Aggregate with :func:`macro_span_f1`."""
    tally: dict = {}
    t = np.full(doc_len, -1, dtype=np.int64)
    p = np.full(doc_len, -2, dtype=np.int64)
    names: list = []

    def idx(lang):
        if lang not in names:
            names.append(lang)
        return names.index(lang)

    for start, end, lang in truth_spans:
        t[start:end] = idx(lang)
    for s in pred_spans:
        p[s["start"]:s["end"]] = idx(s["lang"])
    for lang in names:
        i = names.index(lang)
        tally[lang] = (
            int(np.sum((t == i) & (p == i))),
            int(np.sum((t != i) & (p == i))),
            int(np.sum((t == i) & (p != i))),
        )
    return tally


def macro_span_f1(tallies) -> float:
    """Macro-averaged byte F1 over the languages appearing in a corpus'
    per-document :func:`span_byte_f1` tallies."""
    agg: dict = {}
    for tally in tallies:
        for lang, (tp, fp, fn) in tally.items():
            a = agg.setdefault(lang, [0, 0, 0])
            a[0] += tp
            a[1] += fp
            a[2] += fn
    f1s = []
    for lang, (tp, fp, fn) in agg.items():
        if tp + fn == 0:
            continue  # language never in truth: precision-only ghost
        denom = 2 * tp + fp + fn
        f1s.append(2 * tp / denom if denom else 0.0)
    return float(np.mean(f1s)) if f1s else 0.0


def add_noise(docs, rate=0.12, seed=17):
    """Typo/byte noise: per word, with probability ``rate``, one random edit
    (replace a char with an ascii letter, delete a char, or swap adjacent
    chars) — the web-text corruption a deployed langid system sees."""
    rng = np.random.default_rng(seed)
    ascii_letters = np.asarray(list("abcdefghijklmnopqrstuvwxyz"))
    out = []
    for d in docs:
        parts = d.split(" ")
        for k, w in enumerate(parts):
            if not w or rng.random() >= rate:
                continue
            op = rng.integers(0, 3)
            j = int(rng.integers(0, len(w)))
            if op == 0:  # replace
                parts[k] = w[:j] + str(rng.choice(ascii_letters)) + w[j + 1:]
            elif op == 1:  # delete
                parts[k] = w[:j] + w[j + 1:]
            elif len(w) > 1:  # swap adjacent
                j = min(j, len(w) - 2)
                parts[k] = w[:j] + w[j + 1] + w[j] + w[j + 2:]
        out.append(" ".join(parts))
    return out


# Confusable pairs for the harder accuracy legs, in preference order: the
# classic Romance/Germanic confusions when the config's language set has
# them, else the en/de fallback every config contains (en/de are siblings
# in the hard corpus's germanic family).
_CONFUSABLE_PAIRS = [("pt", "es"), ("nl", "de"), ("sv", "de"), ("en", "de")]


def accuracy_legs(model, cfg, langs, ref_scorer=None):
    """Hard accuracy legs with headroom (VERDICT r4 #3): 20-120-byte short
    docs, typo-noised short docs, sibling-language confusion at short
    length, and graded code-switching (90/10 and 70/30 dominant-label
    probes). Each leg also reports the REFERENCE SEMANTICS' own accuracy
    (``*_ref``, via the per-row baseline on a subsample) so device-vs-
    reference deltas are visible leg by leg — the corpus is tuned so the
    reference itself scores ~0.7-0.97 here, not 1.0.
    Ref metric: BASELINE 'accuracy parity vs CPU'; the reference has no
    length normalization (LanguageDetectorModel.scala:131-156), so short
    noisy docs are its weak spot too."""
    from spark_languagedetector_tpu import Table as _T

    col = model.get_output_col()
    if ref_scorer is None:  # reuse run_config's scorer when handed one —
        ref_scorer = _baseline_scorer(model)  # rebuilding the config-5
    model_langs = list(model.profile.languages)  # bucket map costs seconds

    def acc(docs, labels, key, legs, ref_docs=300):
        out = model.transform(_T({"fulltext": docs}))
        legs[key + "_accuracy"] = round(
            float(np.mean([a == b for a, b in zip(out.column(col), labels)])), 4
        )
        ref_labels = [
            model_langs[int(np.argmax(ref_scorer(t)))] for t in docs[:ref_docs]
        ]
        legs[key + "_ref"] = round(
            float(np.mean([a == b for a, b in zip(ref_labels, labels)])), 4
        )

    legs: dict = {}
    # 2000 docs: covers 176 languages at ~11 docs each; uniform 20-120B.
    sd_docs, sd_labels = make_corpus(langs, 2000, seed=9, len_range=(20, 120))
    acc(sd_docs, sd_labels, "shortdoc", legs)
    noisy = add_noise(sd_docs[:1000], rate=0.12, seed=17)
    acc(noisy, sd_labels[:1000], "noisy", legs)
    pairs = [p for p in _CONFUSABLE_PAIRS if p[0] in langs and p[1] in langs]
    if pairs:
        clangs = sorted({l for p in pairs for l in p})
        cd, cl = make_corpus(clangs, 600, seed=10, len_range=(20, 120))
        acc(cd, cl, "confusable", legs)
        a, b = pairs[0]
        mixed = make_mixed_corpus(a, b, 300, mean_len=400, frac_a=0.7, seed=11)
        acc(mixed, [a] * len(mixed), "mixed_dominant", legs)
        cs90 = make_mixed_corpus(a, b, 300, mean_len=200, frac_a=0.9, seed=18)
        acc(cs90, [a] * len(cs90), "codeswitch90", legs)
        legs["confusable_pair"] = f"{a}/{b}"
        # codeswitch_seg: the same confusable pair, block-structured with
        # KNOWN boundaries (make_codeswitch_corpus), measured against the
        # output mode that can actually express the answer — whole-doc
        # argmax caps mixed_dominant structurally (a one-label column
        # cannot be right about a two-language document), while the
        # segment decode is scored on byte-span F1 and on whether the
        # top-k candidate set covers every language truly present
        # (docs/SEGMENTATION.md). Direct decoder call on the model's
        # existing runner: no param flip, no profile copy, no recompile.
        from spark_languagedetector_tpu.ops.encoding import texts_to_bytes
        from spark_languagedetector_tpu.segment import (
            SegmentOptions,
            segment_documents,
        )

        seg_docs, seg_truth = make_codeswitch_corpus([a, b], 60, seed=29)
        seg_bytes = texts_to_bytes(
            seg_docs, model.get("predictEncoding")
        )
        results = segment_documents(
            model._get_runner(), seg_bytes, model_langs,
            options=SegmentOptions(),
            calibration=getattr(model, "calibration", None),
        )
        def clamped_tally(tr, r, d):
            # Spans partition the SCORED doc (maxScoreBytes truncation
            # included when a caller left the cap on): score F1 over the
            # bytes the decoder actually saw.
            scored = r["spans"][-1]["end"] if r["spans"] else 0
            scored = min(scored, len(d))
            tr = [
                (s, min(e, scored), l) for s, e, l in tr if s < scored
            ]
            return span_byte_f1(tr, r["spans"], scored)

        legs["codeswitch_seg_f1"] = round(macro_span_f1(
            clamped_tally(tr, r, d)
            for tr, r, d in zip(seg_truth, results, seg_bytes)
        ), 4)
        legs["codeswitch_seg_topk_cover"] = round(float(np.mean([
            all(
                lang in {e["lang"] for e in r["topk"]}
                for lang in {s[2] for s in tr}
            )
            for tr, r in zip(seg_truth, results)
        ])), 4)
    return legs


# ------------------------------------------------- reference CPU baseline ----
def baseline_score(text: str, gram_map: dict, num_langs: int, gram_lengths):
    """Reference hot-loop semantics: per-window map lookup + accumulate."""
    data = text.encode("utf-8")
    acc = [0.0] * num_langs
    for n in gram_lengths:
        if len(data) >= n:
            for i in range(len(data) - n + 1):
                vec = gram_map.get(data[i : i + n])
                if vec is not None:
                    for j in range(num_langs):
                        acc[j] += vec[j]
        elif data:
            vec = gram_map.get(data)
            if vec is not None:
                for j in range(num_langs):
                    acc[j] += vec[j]
    return acc


def _bucket_map(model):
    """id → weight-list map for hashed/cuckoo profiles (per-row baseline)."""
    return {
        int(i): model.profile.weights[r].tolist()
        for r, i in enumerate(model.profile.ids)
    }


def baseline_score_ids(text: str, bucket_map: dict, spec, num_langs: int):
    data = text.encode("utf-8")
    acc = [0.0] * num_langs
    for n in spec.gram_lengths:
        if len(data) >= n:
            windows = (data[i : i + n] for i in range(len(data) - n + 1))
        elif data:
            windows = (data,)
        else:
            windows = ()
        for w in windows:
            vec = bucket_map.get(spec.gram_to_id(w))
            if vec is not None:
                for j in range(num_langs):
                    acc[j] += vec[j]
    return acc


def usable_cpus() -> int:
    """CPUs this process may actually run on — cgroup/taskset-aware, so the
    multi-thread denominator doesn't oversubscribe (and thus understate the
    host) in restricted environments."""
    try:
        return len(os.sched_getaffinity(0)) or 1
    except AttributeError:  # non-Linux
        return os.cpu_count() or 1


# ------------------------------------------------- compiled C++ baseline ----
def _cpp_key_vecs(model, cfg):
    """(keys, vecs) for the compiled reference-shape baseline's gram map.

    Exact profiles expose their string-keyed gram map directly
    (profile.gram_probabilities — the reference's Map[gram -> vector] form).
    Hashed profiles (config 5) have lossy bucket ids, so the map the
    reference would hold is reconstructed from the training corpus: every
    distinct training gram whose bucket survived top-k selection, weighted
    by its bucket's row (collided grams share a row, exactly as hashing
    merged them during fit).
    """
    prof = model.profile
    spec = prof.spec
    if spec.mode == "exact":
        gm = prof.gram_probabilities
        keys = list(gm)
        return keys, np.asarray([gm[k] for k in keys], dtype=np.float64)

    from spark_languagedetector_tpu import native
    from spark_languagedetector_tpu.ops.vocab import window_ids_numpy

    prof = prof.compacted()  # no-op unless the profile is the dense form
    langs = language_names(cfg["n_langs"])
    docs, _ = make_corpus(langs, cfg["train_per_lang"] * len(langs), seed=1)
    docs_b = [d.encode("utf-8") for d in docs]
    pad_to = max(len(d) for d in docs_b)
    batch, lengths = native.pack_batch(docs_b, pad_to)
    prof_ids = np.asarray(prof.ids, dtype=np.int64)
    keys: list[bytes] = []
    rows: list[np.ndarray] = []
    for n in spec.gram_lengths:
        ids = window_ids_numpy(batch, n, spec)
        W = ids.shape[1]
        valid = (np.arange(W)[None, :] + n) <= lengths[:, None]
        pos = np.searchsorted(prof_ids, ids)
        member = prof_ids[np.clip(pos, 0, len(prof_ids) - 1)] == ids
        b_idx, w_idx = np.nonzero(valid & member)
        if not b_idx.size:
            continue
        windows = np.lib.stride_tricks.sliding_window_view(batch, n, axis=1)[
            b_idx, w_idx
        ]
        uniq = np.unique(windows, axis=0)
        uids = window_ids_numpy(uniq, n, spec)[:, 0]
        urows = np.searchsorted(prof_ids, uids)
        keys.extend(u.tobytes() for u in uniq)
        rows.append(urows)
    rowsv = np.concatenate(rows) if rows else np.zeros(0, np.int64)
    return keys, np.asarray(prof.weights, dtype=np.float64)[rowsv]


def time_cpp_baseline(model, cfg, sub, label_docs=None):
    """(docs/s single-thread, docs/s multi-thread, labels, map size) for the
    compiled baseline.

    Times the C++ scorer over the parity subset (best of >= 3 reps or 0.5s
    of wall clock, whichever is more) on one thread — the per-row-executor
    stand-in for the reference's JVM UDF hot loop — and once more with
    ``os.cpu_count()`` threads (``vs_cpp_mt``: the whole-host denominator,
    since the reference's transform is cluster-parallel by contract).
    Methodology note: best-of-reps favors the C++ side relative to the
    single-pass pure-Python denominator in time_baselines — the asymmetry
    DEFLATES vs_cpp (conservative for the device's claim), and is kept
    because the C++ pass is cheap enough to repeat while the Python pass
    costs minutes. Returns (None, None, None, None) when the native library
    is unavailable (bench still reports the Python denominators)."""
    try:
        from spark_languagedetector_tpu import native

        keys, vecs = _cpp_key_vecs(model, cfg)
        rs = native.RefScorer(keys, vecs)
    except Exception as e:  # measurement tool: degrade, don't kill the config
        print(
            json.dumps({"cpp_baseline_unavailable": f"{type(e).__name__}: {e}"}),
            file=sys.stderr,
            flush=True,
        )
        return None, None, None, None
    try:
        docs_b = [t.encode("utf-8") for t in sub]
        glens = model.profile.spec.gram_lengths
        # ``label_docs``: agreement labels over different docs than the
        # timed ones (maxScoreBytes configs check agreement on the
        # truncated bytes while timing the full-doc reference behavior).
        label_b = (
            docs_b
            if label_docs is None
            else [t.encode("utf-8") for t in label_docs]
        )
        labels = rs.score(label_b, glens)

        def best_of(n_threads: int) -> float:
            best, reps, t_total = 0.0, 0, 0.0
            while (t_total < 0.5 or reps < 3) and reps < 10:
                t0 = time.perf_counter()
                rs.score(docs_b, glens, n_threads=n_threads)
                dt = time.perf_counter() - t0
                t_total += dt
                reps += 1
                best = max(best, len(docs_b) / dt)
            return best

        best = best_of(1)
        best_mt = best_of(usable_cpus())
        return best, best_mt, labels, len(keys)
    finally:
        rs.close()


def _fit_stage_delta(before: dict, after: dict) -> dict:
    """Per-fit-stage (pack/put/count/topk/collect/merge) deltas between two
    ``stage_summary`` snapshots: dispatch count, wall total, and the fenced
    device total when present. Deltas — not a registry reset — so the
    config-wide telemetry block later in ``run_config`` keeps its cumulative
    score-path aggregates. The wire (pack+put) and the kernel (count) land
    in separate rows, so the two are never conflated again (the
    PERFORMANCE.md §2 reconciliation lesson, applied to fit)."""
    out = {}
    for path, entry in after.items():
        if not (path == "fit" or path.startswith("fit/")):
            continue
        b = before.get(path, {})
        cnt = entry.get("count", 0) - b.get("count", 0)
        if cnt <= 0:
            continue
        row = {
            "count": cnt,
            "total_s": round(
                entry.get("total_s", 0.0) - b.get("total_s", 0.0), 4
            ),
        }
        if "device_total_s" in entry:
            row["device_total_s"] = round(
                entry["device_total_s"] - b.get("device_total_s", 0.0), 4
            )
        out[path] = row
    return out


def fit_compute_only(cfg, langs, docs, labels, reps=6):
    """§5-methodology compute-only device fit rate: every planned batch is
    pre-packed and resident before the clock starts, the timed region is the
    count-step chain alone, and each rep is bounded by a synchronous fetch
    of a data-dependent scalar (the count table's sum). Per-rep distinct
    ``lang_ids`` buffers keep any (executable, args) pair from repeating, so
    the relay's result cache can't fake progress (docs/PERFORMANCE.md §5).
    Reports best AND median docs/s plus the spec the kernel actually counted
    (exact n=4..5 configs measure their device dense half, gram lengths ≤ 3
    — the split fit's host half is excluded by construction).
    """
    import jax
    import jax.numpy as jnp

    from spark_languagedetector_tpu import LanguageDetector, native
    from spark_languagedetector_tpu.ops import fit_tpu
    from spark_languagedetector_tpu.ops.encoding import texts_to_bytes
    from spark_languagedetector_tpu.ops.fit_pipeline import plan_fit_batches
    from spark_languagedetector_tpu.ops.vocab import (
        EXACT,
        MAX_DEVICE_ID_GRAM_LEN,
        VocabSpec,
    )

    det = (
        LanguageDetector(langs, cfg["gram_lengths"], cfg["k"])
        .set_vocab_mode(cfg["vocab"])
        .set_hash_bits(20)
    )
    spec = det._vocab_spec()
    if spec.mode == EXACT and max(spec.gram_lengths) > MAX_DEVICE_ID_GRAM_LEN:
        low = tuple(n for n in spec.gram_lengths if n <= MAX_DEVICE_ID_GRAM_LEN)
        spec = VocabSpec(EXACT, low)
    lang_to_idx = {l: i for i, l in enumerate(langs)}
    lang_idx = np.asarray([lang_to_idx[l] for l in labels], dtype=np.int32)
    items, item_langs, plan, _, _ = plan_fit_batches(
        texts_to_bytes(docs), lang_idx, spec
    )
    if not plan:
        return {}
    num_langs = len(langs)
    resident = []
    for sel, pad_to in plan:
        b, ln = native.pack_batch([items[k] for k in sel], pad_to)
        resident.append((jax.device_put(b), jax.device_put(ln), item_langs[sel]))
    # Distinct lang buffers per rep (plus one warm-up set): rotating the
    # language assignment changes the scatter columns, not the work shape.
    variants = [
        [
            jax.device_put(((lg + r) % num_langs).astype(np.int32))
            for (_, _, lg) in resident
        ]
        for r in range(reps + 1)
    ]
    on_accel = jax.devices()[0].platform != "cpu"
    step = fit_tpu._fit_dense_step_donated if on_accel else fit_tpu.fit_dense_step
    V = spec.id_space_size

    def one_pass(r) -> float:
        acc = jnp.zeros((V, num_langs), dtype=jnp.int32)
        for (b, ln, _), lg in zip(resident, variants[r]):
            acc = step(b, ln, lg, acc, spec=spec, num_langs=num_langs)
        return float(jnp.sum(acc))  # sync scalar fetch bounds the region

    one_pass(reps)  # warm/compile with the spare variant set
    n = len(docs)
    rates = []
    for r in range(reps):
        t0 = time.perf_counter()
        one_pass(r)
        rates.append(n / (time.perf_counter() - t0))
    return {
        "fit_compute_docs_per_s": round(max(rates), 1),
        "fit_compute_docs_per_s_med": round(float(np.median(rates)), 1),
        "fit_compute_spec": f"{spec.mode}:" + ",".join(
            str(g) for g in spec.gram_lengths
        ),
    }


def fit_bench(cfg, langs):
    """Fit throughput: the host fit vs the TPU-native device fit at this
    config's scale (VERDICT r4 #5 — the reference's fit is its slowest path:
    N shuffles + per-language jobs, LanguageDetector.scala:145-165; nothing
    previously measured whether the device fit actually beats the host fit).

    Times the full user-facing ``LanguageDetector.fit`` both ways on the
    config's training corpus — device timed twice, cold then warm, with the
    warm number reported (compiles are one-off; ``fit_device_cold_s`` keeps
    the compile cost visible). Gated by the same cross-check the test suite
    uses (ids exact, weights allclose 1e-6): on mismatch, no perf is
    reported — a loud marker replaces it.

    The warm device fit additionally reports ``fit_wire_mb`` (bytes the
    pipelined ingest actually shipped), ``fit_stages`` (pack vs put vs count
    vs topk vs collect wall totals, from telemetry deltas), and the
    §5-methodology compute-only rate (:func:`fit_compute_only`) — so the
    wire and the kernel are separately attributable in every artifact.
    """
    from spark_languagedetector_tpu import LanguageDetector, Table
    from spark_languagedetector_tpu.telemetry import REGISTRY

    try:
        docs, labels = make_corpus(
            langs, cfg["train_per_lang"] * len(langs), seed=1
        )
        table = Table({"lang": labels, "fulltext": docs})
        n = len(docs)

        def build():
            return (
                LanguageDetector(langs, cfg["gram_lengths"], cfg["k"])
                .set_vocab_mode(cfg["vocab"])
                .set_hash_bits(20)
            )

        t0 = time.perf_counter()
        host_model = build().set_fit_backend("cpu").fit(table)
        t_host = time.perf_counter() - t0
        t0 = time.perf_counter()
        dev_model = build().set_fit_backend("device").fit(table)
        t_dev_cold = time.perf_counter() - t0
        stages_before = REGISTRY.stage_summary()
        counters_before = REGISTRY.snapshot()["counters"]
        wire_before = counters_before.get("fit/wire_bytes", 0)
        collect_before = counters_before.get("fit/collect_bytes", 0)
        t0 = time.perf_counter()
        dev_model = build().set_fit_backend("device").fit(table)
        t_dev = time.perf_counter() - t0
        stages = _fit_stage_delta(stages_before, REGISTRY.stage_summary())
        counters_after = REGISTRY.snapshot()["counters"]
        wire_mb = (counters_after.get("fit/wire_bytes", 0) - wire_before) / 1e6
        # Winner-rows-only collect: bytes the finalize actually pulled back
        # vs the full [V, L] table the pre-device-finalize fit fetched
        # (docs/PERFORMANCE.md §8). The ratio is only well-defined for
        # single-dense-table specs (the split exact n>=4 fit counts its
        # long grams on host).
        collect_bytes = (
            counters_after.get("fit/collect_bytes", 0) - collect_before
        )
        spec = build()._vocab_spec()
        from spark_languagedetector_tpu.ops.vocab import (
            EXACT as _EXACT,
            MAX_DEVICE_ID_GRAM_LEN as _MAXDEV,
        )

        dense_spec = not (
            spec.mode == _EXACT and max(spec.gram_lengths) > _MAXDEV
        )
        ids_match = np.array_equal(
            host_model.profile.ids, dev_model.profile.ids
        )
        w_match = ids_match and np.allclose(
            host_model.profile.weights, dev_model.profile.weights,
            rtol=1e-6, atol=1e-7,
        )
        if not w_match:
            return {"fit_device_mismatch": True}
        out = {
            "fit_docs_per_s_host": round(n / t_host, 1),
            "fit_docs_per_s_device": round(n / t_dev, 1),
            "fit_device_cold_s": round(t_dev_cold, 1),
            "fit_train_docs": n,
            "fit_wire_mb": round(wire_mb, 2),
            "fit_collect_bytes": int(collect_bytes),
            "fit_stages": stages,
        }
        if dense_spec and collect_bytes:
            table_bytes = spec.id_space_size * len(langs) * 4
            out["fit_collect_table_bytes"] = int(table_bytes)
            out["fit_collect_ratio"] = round(collect_bytes / table_bytes, 6)
        out.update(fit_compute_only(cfg, langs, docs[:4096], labels[:4096]))
        return out
    except Exception as e:  # diagnostic leg: degrade, don't kill the config
        print(
            json.dumps({"fit_bench_error": f"{type(e).__name__}: {e}"}),
            file=sys.stderr,
            flush=True,
        )
        return {}


def hashed_vs_exact(model, cfg, langs):
    """Collision cost of the 2^20 exact12 hashed vocab (config 5), measured
    against an EXACT n=1..5 model fitted on the same corpus with the same k
    (SURVEY §7.4: hashed mode changes accuracy and must be validated, not
    assumed). Reports label agreement on the full-length eval corpus plus
    the accuracy delta on the short-doc leg, where scarce signal makes
    collisions actually bite."""
    from spark_languagedetector_tpu import Table as _T

    try:
        exact_model = fit_model(dict(cfg, vocab="exact"))
        col = model.get_output_col()

        def labels_of(m, docs):
            return list(m.transform(_T({"fulltext": docs})).column(col))

        docs, truth = make_corpus(langs, 2000, seed=12)
        h, e = labels_of(model, docs), labels_of(exact_model, docs)
        agree = float(np.mean([a == b for a, b in zip(h, e)]))
        sdocs, struth = make_corpus(langs, 2000, seed=13, len_range=(20, 120))
        hs, es = labels_of(model, sdocs), labels_of(exact_model, sdocs)
        acc_h = float(np.mean([a == b for a, b in zip(hs, struth)]))
        acc_e = float(np.mean([a == b for a, b in zip(es, struth)]))
        return {
            "hashed_vs_exact_agreement": round(agree, 4),
            "hashed_vs_exact_shortdoc_delta": round(acc_h - acc_e, 4),
            "exact_shortdoc_accuracy": round(acc_e, 4),
        }
    except Exception as e:  # diagnostic leg: degrade, don't kill the config
        print(
            json.dumps({"hashed_vs_exact_error": f"{type(e).__name__}: {e}"}),
            file=sys.stderr,
            flush=True,
        )
        return {}


def fused_leg(model, cfg, langs, base_pred, sub, cpp_mt_dps, eval_docs):
    """Fused-megakernel leg (config 1, ROADMAP item 3): the same profile
    scored through ``strategy='fused'`` at f32, int8, and int16 tables.

    Reports per-variant ``table_bytes`` (+ the f32 layout bytes and the
    quantized ratio), throughput on TPU hardware (with ``vs_cpp_mt``
    against the already-measured multi-thread C++ denominator — the
    acceptance target is ≥ 3), and the fused program's roofline verdict
    from XLA's cost model joined with measured per-dispatch seconds
    (recorded into a private registry so the config's cumulative capture
    keeps describing the main strategy). On the CPU substrate the kernel
    runs in Pallas interpret mode over a small parity subset: the
    agreement gates below still bite, throughput is reported as absent.

    HARD GATES (SystemExit, like the main parity gate): int16 labels must
    match the reference baseline exactly; int8 labels must agree with the
    f32 fused labels on ≥ 99.9% of docs; the int8 table must be ≤ 0.3× the
    f32 layout bytes; and on TPU hardware fused vs_cpp_mt must reach 3.
    """
    import jax as _jax

    from spark_languagedetector_tpu.api.runner import (
        BatchRunner,
        rows_for_bucket,
    )
    from spark_languagedetector_tpu.telemetry import REGISTRY
    from spark_languagedetector_tpu.telemetry import cost as cost_mod
    from spark_languagedetector_tpu.telemetry.registry import Registry

    try:
        weights, lut, cuckoo = model.profile.device_membership()
        spec = model.profile.spec
        on_tpu = _jax.default_backend() == "tpu"
        out = {
            "roofline_bound_before": REGISTRY.stage_summary()
            .get("score/dispatch", {})
            .get("roofline_bound"),
        }
        # Parity sample: the capped parity docs (aligned with base_pred).
        # Interpret mode is orders of magnitude slower than Mosaic, so the
        # CPU substrate gates semantics on a subset and skips timing.
        parity_docs = [t.encode("utf-8") for t in sub]
        if not on_tpu:
            parity_docs = parity_docs[:48]
        base = list(base_pred[: len(parity_docs)]) if base_pred else []
        f32_labels = None
        for quant in (None, "int8", "int16"):
            key = quant or "f32"
            runner = BatchRunner(
                weights=weights, lut=lut, cuckoo=cuckoo, spec=spec,
                strategy="fused", quantization=quant,
            )
            runner._cost_recorded = True  # keep the shared gauges clean
            _, _, _, _, _, table_bytes, f32_bytes = runner._fused_state()
            entry = {"table_bytes": table_bytes}
            if quant:
                entry["table_bytes_ratio"] = round(table_bytes / f32_bytes, 4)
            else:
                out["table_bytes_f32"] = f32_bytes
            labels = runner.predict_ids(parity_docs)
            if quant is None:
                f32_labels = labels
                if base:
                    entry["argmax_parity"] = float(np.mean(
                        [i == p for i, p in zip(labels.tolist(), base)]
                    ))
            else:
                entry["agreement_vs_f32"] = float(
                    np.mean(labels == f32_labels)
                )
                if base:
                    entry["argmax_parity"] = float(np.mean(
                        [i == p for i, p in zip(labels.tolist(), base)]
                    ))
            if on_tpu:
                docs_b = [t.encode("utf-8") for t in eval_docs]
                runner.predict_ids(docs_b)  # compile every shape first
                times = []
                for _ in range(4):
                    t0 = time.perf_counter()
                    runner.predict_ids(docs_b)
                    times.append(time.perf_counter() - t0)
                dps = len(docs_b) / min(times)
                entry["docs_per_s"] = round(dps, 1)
                if cpp_mt_dps:
                    entry["vs_cpp_mt"] = round(dps / cpp_mt_dps, 2)
            # Fused-program roofline from XLA's cost model at the real
            # dispatch shape, joined with measured per-dispatch seconds —
            # in a private registry so the config capture's score/dispatch
            # gauges keep describing the main strategy's program.
            from spark_languagedetector_tpu.ops.encoding import (
                bucket_length,
            )

            # The SMALLEST covering bucket — the shape the timed score
            # below actually dispatches at, so the cost/time join is
            # shape-consistent.
            longest = max((len(d) for d in parity_docs), default=1)
            pad_to = bucket_length(
                min(longest, runner.max_chunk) or 1, runner.length_buckets
            )
            rows = min(len(parity_docs), rows_for_bucket(
                pad_to, runner.batch_size, runner.batch_bytes
            ))
            reg = Registry()
            cost = cost_mod.record_runner_cost(runner, rows, pad_to, reg)
            if cost:
                t0 = time.perf_counter()
                runner.score(parity_docs[:rows])
                per_dispatch_s = time.perf_counter() - t0
                peaks = cost_mod.peak_rates(_jax.default_backend())
                if peaks and per_dispatch_s > 0:
                    fu = cost.get("flops", 0.0) / per_dispatch_s / peaks[0]
                    bu = (
                        cost.get("bytes_accessed", 0.0)
                        / per_dispatch_s / peaks[1]
                    )
                    entry["roofline_bound"] = (
                        "compute" if fu >= bu else "memory"
                    )
                    entry["est_bytes_utilization"] = round(bu, 6)
            out[key] = entry

        # ---- hard gates ---------------------------------------------
        if base and out["int16"].get("argmax_parity", 1.0) < 1.0:
            raise SystemExit(
                f"fused int16 parity violated on {cfg['label']}: "
                f"{out['int16']['argmax_parity']:.4f} — int16 quantization "
                "must not move any argmax on the bench suite"
            )
        if out["int8"].get("agreement_vs_f32", 1.0) < 0.999:
            raise SystemExit(
                f"fused int8 agreement violated on {cfg['label']}: "
                f"{out['int8']['agreement_vs_f32']:.4f} < 0.999"
            )
        if out["int8"]["table_bytes"] > 0.3 * out["table_bytes_f32"]:
            raise SystemExit(
                f"fused int8 table_bytes {out['int8']['table_bytes']} "
                f"exceeds 0.3x the f32 layout ({out['table_bytes_f32']})"
            )
        if on_tpu and cpp_mt_dps:
            best = max(
                out[k].get("vs_cpp_mt", 0.0) for k in ("f32", "int8", "int16")
            )
            out["vs_cpp_mt_target"] = 3.0
            if best < 3.0:
                raise SystemExit(
                    f"fused vs_cpp_mt {best:.2f} below the 3.0 target on "
                    f"{cfg['label']} (ROADMAP item 3 acceptance)"
                )
        return {"fused": out}
    except SystemExit:
        raise
    except Exception as e:  # diagnostic leg: degrade, don't kill the config
        print(
            json.dumps({"fused_error": f"{type(e).__name__}: {e}"}),
            file=sys.stderr,
            flush=True,
        )
        return {}


# ------------------------------------------------------------- telemetry ----
def telemetry_setup():
    """Wire this config's telemetry: jax.monitoring hooks + a JSONL sink.

    Returns the JSONL path the run records into. LANGDETECT_METRICS_SINK
    wins when it already declares a jsonl sink (attached at package
    import); otherwise a per-process file under the system tmpdir is
    attached (per-config calls reuse the first sink). Aggregates are reset
    per call so each config's breakdown block is self-contained — span
    percentiles from config N must not dilute config N+1's (the JSONL
    event log still carries everything, sinks survive the reset).
    """
    import tempfile

    from spark_languagedetector_tpu.telemetry import REGISTRY, install_jax_hooks
    from spark_languagedetector_tpu.telemetry.export import JsonlSink

    install_jax_hooks()
    REGISTRY.reset()
    for sink in REGISTRY.sinks:
        if getattr(sink, "kind", "") == "jsonl":
            return sink.path
    path = os.path.join(
        tempfile.gettempdir(), f"bench_telemetry_{os.getpid()}.jsonl"
    )
    REGISTRY.add_sink(JsonlSink(path))
    return path


def telemetry_block(jsonl_path: str) -> dict:
    """The per-config telemetry block for the BENCH_* artifact: the JSONL
    path plus the per-stage breakdown since this config's telemetry_setup
    (count / total seconds / percentiles per span path), so rounds get
    stage-level trajectories instead of one end-to-end docs/s. Device
    gauges are sampled and the snapshot sinks flushed on the way out."""
    from spark_languagedetector_tpu.telemetry import (
        REGISTRY,
        sample_device_gauges,
    )

    sample_device_gauges()
    REGISTRY.flush()
    from spark_languagedetector_tpu.exec import config as exec_config

    out = {
        "jsonl": jsonl_path,
        "stages": REGISTRY.stage_summary(),
        # The audited effective config (same block /varz serves): every
        # knob's live value + provenance, so a bench artifact records
        # exactly which lattice/budget/window produced its numbers.
        "effective_config": exec_config.effective_config(),
    }
    # Redundancy-eliminator evidence (docs/PERFORMANCE.md §10): present
    # whenever this config's run saw dedup or serve-cache traffic.
    counters = REGISTRY.snapshot()["counters"]
    rows_in = int(counters.get("dedup/rows_in", 0))
    lookups = int(counters.get("cache/lookups", 0))
    if rows_in or lookups:
        out["redundancy"] = {
            "dedup_rows_in": rows_in,
            "dedup_rows_unique": int(counters.get("dedup/rows_unique", 0)),
            "dedup_unique_ratio": round(
                int(counters.get("dedup/rows_unique", 0)) / rows_in, 6
            ) if rows_in else None,
            "cache_lookups": lookups,
            "cache_hits": int(counters.get("cache/hits", 0)),
            "cache_hit_rate": round(
                int(counters.get("cache/hits", 0)) / lookups, 6
            ) if lookups else None,
            "bytes_saved": int(counters.get("dedup/bytes_saved", 0))
            + int(counters.get("cache/bytes_saved", 0)),
        }
    return out


def smoke_telemetry(jsonl_path: str | None = None) -> dict:
    """Tiny CPU-safe fit + score pass with telemetry on: the bench's smoke
    path. Writes span events to ``jsonl_path`` (default: a fresh tmp file),
    returns the result dict with the telemetry block. Used by
    ``python bench.py --smoke-telemetry`` and the tier-1 suite — it must
    stay fast (~seconds) and accelerator-free.

    Also exercises the flight recorder end to end: a streaming pass with a
    failing sink drives the real crash path, and the post-mortem dump's
    path/size land in the result (``flight_recorder`` block) so a smoke
    run proves the whole observability stack, not just the happy path.
    """
    import tempfile

    from spark_languagedetector_tpu import LanguageDetector, Table
    from spark_languagedetector_tpu.telemetry import (
        REGISTRY,
        flightrec,
        install_jax_hooks,
        new_trace_id,
        trace_request,
    )
    from spark_languagedetector_tpu.telemetry.export import JsonlSink

    install_jax_hooks()
    REGISTRY.reset()
    path = jsonl_path or os.path.join(
        tempfile.gettempdir(), f"telemetry_smoke_{os.getpid()}.jsonl"
    )
    sink = JsonlSink(path)
    REGISTRY.add_sink(sink)
    # Arm a recorder for the crash leg unless the env already did; only an
    # armed-by-us recorder is torn down on the way out.
    _owned_recorder = flightrec.active() is None
    if _owned_recorder:
        flightrec.install(
            os.path.join(
                tempfile.gettempdir(), f"flightrec_smoke_{os.getpid()}"
            )
        )
    try:
        langs = language_names(3)
        docs, labels = make_corpus(langs, 60, mean_len=200, seed=3)
        det = LanguageDetector(langs, [1, 2], 200)
        model = det.fit(Table({"lang": labels, "fulltext": docs}))
        score_trace = new_trace_id()
        with trace_request(score_trace):
            out = model.transform(Table({"fulltext": docs}))
        assert len(out.column(model.get_output_col())) == len(docs)

        # Flight-recorder leg: a sink that dies mid-stream takes the real
        # crash path (run_stream's except hook dumps the ring).
        from spark_languagedetector_tpu.stream.microbatch import (
            memory_source,
            run_stream,
        )

        def dying_sink(table):
            raise RuntimeError("smoke-telemetry flight-recorder probe")

        # last_dump_path is process-global: snapshot it first so a stale
        # dump from an earlier crash can't masquerade as this leg's proof.
        dump = None
        prev_dump = flightrec.last_dump_path()
        try:
            run_stream(
                model,
                memory_source([{"fulltext": d} for d in docs[:20]], 10),
                dying_sink,
            )
        except RuntimeError:
            fresh = flightrec.last_dump_path()
            if fresh is not None and fresh != prev_dump:
                dump = fresh
        flight = {"exercised": dump is not None}
        if dump:
            flight["dump"] = dump
            with open(dump, "r", encoding="utf-8") as fh:
                flight["events"] = sum(1 for _ in fh) - 1  # minus header
        # Dispatch-cost gauges land off the dispatch path (cold-start
        # plane): join so the captured stage breakdown includes them.
        cost_thread = getattr(model._get_runner(), "_cost_thread", None)
        if cost_thread is not None:
            cost_thread.join(timeout=120)
        return {
            "smoke": True,
            "docs": len(docs),
            "flight_recorder": flight,
            "telemetry": {**telemetry_block(path), "trace_id": score_trace},
        }
    finally:
        REGISTRY.remove_sink(sink)
        if _owned_recorder:
            flightrec.uninstall()


def smoke_chaos(jsonl_path: str | None = None) -> dict:
    """CPU-safe chaos schedule: the bench's recovery-behavior smoke path.

    Two scripted legs under seeded ``FaultPlan``s (seconds, no
    accelerator), reporting recovery counts and the degraded-mode time
    share so regressions in recovery behavior show up in the perf
    trajectory next to the throughput numbers:

      1. **breaker leg** — a model with an aggressive env-tuned breaker
         takes an injected dispatch fault, trips open, serves exact
         results through the degradation ladder, then recovers to the
         fast path once the cooldown elapses;
      2. **stream leg** — a streaming run under transient stream +
         dispatch faults and one poison batch, with a DLQ and a
         checkpoint: the query must complete, outputs must equal the
         fault-free oracle minus exactly the quarantined poison rows.

    ``oracle_match`` is the hard gate — ``main()`` exits nonzero when the
    chaos run's outputs disagree with the fault-free run.
    """
    import tempfile
    import time as _time

    from spark_languagedetector_tpu import LanguageDetector, Table
    from spark_languagedetector_tpu.resilience import faults
    from spark_languagedetector_tpu.resilience.dlq import DeadLetterQueue
    from spark_languagedetector_tpu.resilience.faults import FaultPlan
    from spark_languagedetector_tpu.resilience.policy import RetryPolicy
    from spark_languagedetector_tpu.stream.microbatch import (
        memory_source,
        run_stream,
    )
    from spark_languagedetector_tpu.telemetry import REGISTRY
    from spark_languagedetector_tpu.telemetry.export import JsonlSink

    REGISTRY.reset()
    path = jsonl_path or os.path.join(
        tempfile.gettempdir(), f"chaos_smoke_{os.getpid()}.jsonl"
    )
    sink = JsonlSink(path)
    REGISTRY.add_sink(sink)
    # Leg-1 knobs: breaker trips on the first failure, reopens fast, and
    # the runner policy fails fast (the ladder, not the replay, is under
    # test). Restored before the stream leg builds its runner.
    overrides = {
        "LANGDETECT_BREAKER_THRESHOLD": "1",
        "LANGDETECT_BREAKER_COOLDOWN_S": "0.05",
        "LANGDETECT_RETRY_MAX_ATTEMPTS": "1",
        "LANGDETECT_RETRY_BASE_DELAY_S": "0",
    }
    saved = {k: os.environ.get(k) for k in overrides}

    def _restore():
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    mismatches: list[str] = []
    try:
        langs = language_names(3)
        docs, labels = make_corpus(langs, 60, mean_len=200, seed=3)
        det = LanguageDetector(langs, [1, 2], 200)
        model = det.fit(Table({"lang": labels, "fulltext": docs}))
        rows = [{"fulltext": d} for d in docs]
        oracle: list[str] = []
        run_stream(
            model,
            memory_source(rows, 10),
            lambda t: oracle.extend(t.column("lang").tolist()),
        )
        clean_labels = model.transform(
            Table({"fulltext": docs[:30]})
        ).column("lang").tolist()

        # Leg 1: breaker trip -> degraded ladder -> recovery.
        os.environ.update(overrides)
        m2 = model.copy()  # fresh runner, built under the leg-1 env
        with faults.plan_scope(FaultPlan.parse("seed=7;score/dispatch:error@1")):
            degraded_labels = m2.transform(
                Table({"fulltext": docs[:30]})
            ).column("lang").tolist()
            _time.sleep(0.06)  # past the cooldown: next call probes
            recovered_labels = m2.transform(
                Table({"fulltext": docs[:30]})
            ).column("lang").tolist()
        if degraded_labels != clean_labels:
            mismatches.append("breaker leg: degraded labels diverged")
        if recovered_labels != clean_labels:
            mismatches.append("breaker leg: post-recovery labels diverged")
        _restore()

        # Leg 2: streaming chaos — transient faults + one poison batch,
        # with DLQ + checkpoint.
        plan = FaultPlan.parse(
            "seed=7;stream/batch:error@2;score/dispatch:error@6;"
            "stream/batch:poison=2@3"
        )
        poison = plan.poison_rows(3, 10)  # batch 3 == rows 20-29
        dlq = DeadLetterQueue()
        ck = os.path.join(
            tempfile.gettempdir(), f"chaos_smoke_ck_{os.getpid()}.json"
        )
        if os.path.exists(ck):
            os.remove(ck)
        outputs: list[str] = []
        m3 = model.copy()
        with faults.plan_scope(plan):
            query = run_stream(
                m3,
                memory_source(rows, 10),
                lambda t: outputs.extend(t.column("lang").tolist()),
                retry_policy=RetryPolicy(max_attempts=3, base_delay_s=0.0),
                dlq=dlq,
                checkpoint_path=ck,
            )
        poisoned_global = {20 + r for r in poison}
        expected = [
            lang for i, lang in enumerate(oracle) if i not in poisoned_global
        ]
        if outputs != expected:
            mismatches.append("stream leg: outputs diverged from oracle")
        if len(dlq) != len(poison):
            mismatches.append(
                f"stream leg: DLQ holds {len(dlq)} rows, expected "
                f"{len(poison)}"
            )

        snap = REGISTRY.snapshot()
        counters = snap["counters"]
        stages = REGISTRY.stage_summary()
        degraded_s = sum(
            v["total_s"] for p, v in stages.items()
            if p.split("/")[-1] == "degraded"
        )
        score_s = sum(
            v["total_s"] for p, v in stages.items()
            if p.split("/")[-1] == "score"
        )
        return {
            "smoke_chaos": True,
            "docs": len(docs),
            "oracle_match": not mismatches,
            "mismatches": mismatches,
            "stream": {
                "batches": query.batches,
                "rows": query.rows,
                "quarantined_batches": query.quarantined_batches,
                "checkpoint_committed": query.batches + query.resumed_from,
            },
            "recoveries": {
                "retries": counters.get("resilience/retries", 0),
                "score_retries": counters.get("score/retries", 0),
                "stream_retries": counters.get("stream/retries", 0),
                "faults_injected": counters.get(
                    "resilience/faults_injected", 0
                ),
                "breaker_opened": counters.get(
                    "resilience/breaker_opened", 0
                ),
                "degraded_batches": counters.get(
                    "resilience/degraded_batches", 0
                ),
                "dlq_rows": len(dlq),
            },
            "degraded_time_share": round(
                min(1.0, degraded_s / score_s) if score_s else 0.0, 4
            ),
            "telemetry": telemetry_block(path),
        }
    finally:
        _restore()
        REGISTRY.remove_sink(sink)


def smoke_serve(jsonl_path: str | None = None) -> dict:
    """CPU-safe serving smoke: the online subsystem under concurrent load.

    Spins the whole serve stack up in-process — registry, continuous
    batcher, threaded HTTP server — and drives it with concurrent
    clients over a real socket, including one mid-run hot-swap (through
    ``/admin/swap`` + the persist load path) and one shed burst against
    a shrunken queue bound. Seconds, no accelerator.

    Hard gates (``main()`` exits nonzero): every non-shed request must
    be answered exactly once with scores bit-identical to the direct
    ``BatchRunner.score`` of whichever model version served it
    (``parity_ok``), zero requests may be dropped across the swap
    (``dropped_responses``), the batcher must demonstrably coalesce
    (``coalesced.mean_rows_per_dispatch > 1``), and the shed burst must
    produce explicit 503 rejections (``shed.requests > 0``).
    """
    import tempfile
    import threading

    from spark_languagedetector_tpu import LanguageDetector, Table
    from spark_languagedetector_tpu.ops.encoding import texts_to_bytes
    from spark_languagedetector_tpu.serve import ContinuousBatcher, ModelRegistry
    from spark_languagedetector_tpu.serve.client import ServeClient, ServeHTTPError
    from spark_languagedetector_tpu.serve.server import ServingServer
    from spark_languagedetector_tpu.telemetry import REGISTRY
    from spark_languagedetector_tpu.telemetry.export import JsonlSink

    REGISTRY.reset()
    path = jsonl_path or os.path.join(
        tempfile.gettempdir(), f"serve_smoke_{os.getpid()}.jsonl"
    )
    sink = JsonlSink(path)
    REGISTRY.add_sink(sink)

    # gram_lengths [1,2,3] keep the runner on the gather strategy (the
    # batch-geometry-stable A/B reference), so the bit-exact parity gate
    # below is strategy-sound, not geometry luck — a [1,2] profile would
    # ride the onehot matmul, whose XLA reduction order may flip the last
    # f32 bit between a request's solo geometry and its coalesced one
    # (docs/SERVING.md §1).
    langs = language_names(3)
    docs, labels = make_corpus(langs, 60, mean_len=200, seed=3)
    model_a = LanguageDetector(langs, [1, 2, 3], 200).fit(
        Table({"lang": labels, "fulltext": docs})
    )
    docs_b, labels_b = make_corpus(langs, 60, mean_len=200, seed=9)
    model_b = LanguageDetector(langs, [1, 2, 3], 150).fit(
        Table({"lang": labels_b, "fulltext": docs_b})
    )
    runner_a, runner_b = model_a._get_runner(), model_b._get_runner()

    registry = ModelRegistry()
    v_a = registry.install(model_a)
    batcher = ContinuousBatcher(
        registry, max_wait_ms=5, max_rows=64, max_queue_rows=512
    )
    n_clients, rounds, docs_per_req = 6, 8, 4
    barrier = threading.Barrier(n_clients)
    results: list[tuple[list[str], np.ndarray, str, float]] = []
    errors: list[str] = []
    sheds = [0]
    lock = threading.Lock()
    swap_ms = [0.0]
    v_b: list[str | None] = [None]
    tmpdir = tempfile.mkdtemp(prefix="serve_smoke_model_")

    with ServingServer(registry, port=0, batcher=batcher) as server:
        host, port = server.address
        client = ServeClient(host, port)

        def drive(ci: int) -> None:
            rng = np.random.default_rng(100 + ci)
            for r in range(rounds):
                try:
                    barrier.wait(timeout=30)
                except threading.BrokenBarrierError:
                    pass
                # Thread 0 swaps mid-run (between rounds, while the other
                # five clients keep a request in flight every round).
                if ci == 0 and r == rounds // 2:
                    model_b.save(tmpdir + "/m")
                    t0 = time.perf_counter()
                    v_b[0] = client.swap(tmpdir + "/m")
                    swap_ms[0] = (time.perf_counter() - t0) * 1e3
                    continue
                lo = int(rng.integers(0, len(docs) - docs_per_req))
                texts = docs[lo:lo + docs_per_req]
                t0 = time.perf_counter()
                try:
                    scores, meta = client.score(texts)
                except ServeHTTPError as e:
                    with lock:
                        if e.shed:
                            sheds[0] += 1
                        else:
                            errors.append(f"client {ci} round {r}: {e}")
                    continue
                latency_ms = (time.perf_counter() - t0) * 1e3
                with lock:
                    results.append(
                        (texts, scores, meta["version"], latency_ms)
                    )

        threads = [
            threading.Thread(target=drive, args=(ci,)) for ci in range(n_clients)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        main_sheds = sheds[0]

        # Shed burst: shrink the queue bound and fire concurrent bulk
        # requests faster than the dispatcher drains — the overflow must
        # come back as explicit 503s, never hangs.
        batcher.max_queue_rows = 8
        burst_answered = [0]

        def burst(bi: int) -> None:
            try:
                scores, _ = client.score(
                    docs[:docs_per_req], priority="bulk"
                )
            except ServeHTTPError as e:
                with lock:
                    if e.shed:
                        sheds[0] += 1
                    else:
                        errors.append(f"burst {bi}: {e}")
            else:
                with lock:
                    burst_answered[0] += 1

        burst_threads = [
            threading.Thread(target=burst, args=(bi,)) for bi in range(24)
        ]
        for t in burst_threads:
            t.start()
        for t in burst_threads:
            t.join(timeout=60)
        health = client.healthz()

    # Parity: every answered request must match the direct runner of the
    # version that served it, bit for bit (HTTP included).
    parity_ok = not errors
    for texts, scores, version, _ in results:
        runner = runner_a if version == v_a else runner_b
        want = runner.score(texts_to_bytes(texts))
        if scores.shape != want.shape or not np.array_equal(scores, want):
            parity_ok = False
            errors.append(f"parity mismatch on version {version}")
            break

    expected_responses = n_clients * rounds - 1  # thread 0 spends one on swap
    answered = len(results) + burst_answered[0]
    dropped = expected_responses - len(results) - main_sheds
    versions_served = sorted({v for _, _, v, _ in results})

    snap = REGISTRY.snapshot()
    hists = snap["histograms"]
    rows_h = hists.get("serve/rows_per_dispatch", {})
    total_h = hists.get("serve/total_s", {})
    qwait_h = hists.get("serve/queue_wait_s", {})
    total_requests = answered + sheds[0]
    coalesced_mean = rows_h.get("mean", 0.0) / max(docs_per_req, 1)
    result = {
        "smoke_serve": True,
        "requests": total_requests,
        "answered": answered,
        "dropped_responses": dropped,
        "parity_ok": parity_ok,
        "errors": errors[:5],
        "latency_ms": {
            "p50": round(total_h.get("p50", 0.0) * 1e3, 3),
            "p99": round(total_h.get("p99", 0.0) * 1e3, 3),
            "queue_wait_p99": round(qwait_h.get("p99", 0.0) * 1e3, 3),
        },
        "coalesced": {
            "dispatches": rows_h.get("count", 0),
            "mean_rows_per_dispatch": round(rows_h.get("mean", 0.0), 3),
            "mean_requests_per_dispatch": round(coalesced_mean, 3),
            "max_rows_per_dispatch": rows_h.get("max", 0),
            "rows": snap["counters"].get("serve/coalesced_rows", 0),
            "histogram": rows_h,
        },
        "shed": {
            "requests": sheds[0],
            "rate": round(sheds[0] / max(total_requests, 1), 4),
            "burst_answered": burst_answered[0],
        },
        "swap": {
            "from": v_a,
            "to": v_b[0],
            "wall_ms": round(swap_ms[0], 3),
            "versions_served": versions_served,
        },
        "health": {
            "version": health.get("version"),
            "breaker": health.get("breaker"),
        },
        "telemetry": telemetry_block(path),
    }
    result["ok"] = bool(
        parity_ok
        and dropped == 0
        # Both coalescing signals: rows (the acceptance bar) AND
        # requests per dispatch — the latter is what actually proves
        # coalescing, since every request already carries
        # docs_per_req rows on its own.
        and result["coalesced"]["mean_rows_per_dispatch"] > 1.0
        and result["coalesced"]["mean_requests_per_dispatch"] > 1.0
        and sheds[0] > 0
        and v_b[0] is not None
        and len(versions_served) >= 2
    )
    REGISTRY.remove_sink(sink)
    return result


def smoke_fleet(jsonl_path: str | None = None, *, trimmed: bool = False) -> dict:
    """CPU-safe fleet smoke: replicated serving under chaos.

    Spins up 3 serve replicas (each its own registry + batcher + HTTP
    server) behind the health-checked router and its HTTP front tier,
    then drives the fleet with concurrent socket clients while the
    script: (1) kills a replica mid-traffic and hammers until the router
    demonstrably fails requests over to the survivors, (2) waits for the
    ejection (breaker open) and, after reviving the replica, the
    half-open re-admission, and (3) performs a fleet-wide two-phase
    hot-swap mid-traffic. Clients honor ``Retry-After`` with the seeded
    backoff, so transient fleet-wide sheds are absorbed, not dropped.

    Hard gates (``main()`` exits nonzero): zero dropped responses (every
    request answered despite the kill and the swap), argmax parity
    exactly 1.0 against the direct runner of whichever version served
    each response, at least one observed failover AND ejection AND
    re-admission, and swap atomicity — both versions served, and no
    client stream ever sees the old version again after its first
    new-version response. ``trimmed=True`` is the tier-1-sized variant
    (fewer clients/rounds, same gates).
    """
    import tempfile
    import threading

    from spark_languagedetector_tpu import LanguageDetector, Table
    from spark_languagedetector_tpu.ops.encoding import texts_to_bytes
    from spark_languagedetector_tpu.resilience.policy import RetryPolicy
    from spark_languagedetector_tpu.serve.client import ServeClient, ServeHTTPError
    from spark_languagedetector_tpu.serve.fleet import ServeFleet
    from spark_languagedetector_tpu.serve.quarantine import QuarantineTable
    from spark_languagedetector_tpu.serve.router import RouterServer
    from spark_languagedetector_tpu.telemetry import REGISTRY
    from spark_languagedetector_tpu.telemetry.export import JsonlSink

    REGISTRY.reset()
    path = jsonl_path or os.path.join(
        tempfile.gettempdir(), f"fleet_smoke_{os.getpid()}.jsonl"
    )
    sink = JsonlSink(path)
    REGISTRY.add_sink(sink)

    # gram_lengths [1,2,3] keep every replica runner on the gather
    # strategy (geometry-stable), so label parity vs the direct runner is
    # strategy-sound across coalesce geometries (docs/SERVING.md §1).
    langs = language_names(3)
    docs, labels = make_corpus(langs, 60, mean_len=200, seed=3)
    model_a = LanguageDetector(langs, [1, 2, 3], 200).fit(
        Table({"lang": labels, "fulltext": docs})
    )
    docs_b, labels_b = make_corpus(langs, 60, mean_len=200, seed=9)
    model_b = LanguageDetector(langs, [1, 2, 3], 150).fit(
        Table({"lang": labels_b, "fulltext": docs_b})
    )
    runner_a, runner_b = model_a._get_runner(), model_b._get_runner()
    tmpdir = tempfile.mkdtemp(prefix="fleet_smoke_model_")
    dir_a, dir_b = tmpdir + "/a", tmpdir + "/b"
    model_a.save(dir_a)
    model_b.save(dir_b)

    n_clients = 4 if trimmed else 6
    rounds = 9 if trimmed else 14
    docs_per_req = 4
    kill_round = 2
    revive_round = rounds // 2
    swap_round = rounds - 3
    victim = "r0"  # lowest index: the deterministic tie-break routes the
    # first idle-fleet request here, so post-kill traffic MUST fail over.

    fleet = ServeFleet.from_path(
        dir_a, replicas=3,
        router_kw=dict(
            probe_interval_ms=40.0, breaker_threshold=2,
            breaker_cooldown_s=0.3, probe_timeout_s=2.0,
            drain_timeout_s=5.0,
            # This drill kills replicas under a tiny rotating text set
            # on purpose; quarantine would 422 its own benign traffic.
            # The storm smoke drills quarantine with its own table.
            quarantine=QuarantineTable(0, name="fleet-smoke-off"),
        ),
        max_wait_ms=4, max_rows=64, max_queue_rows=512,
    ).start()
    front = RouterServer(fleet.router, fleet=fleet, port=0).start()
    host, port = front.address
    v_old = "v1"
    v_new: list[str | None] = [None]
    swap_ms = [0.0]

    barrier = threading.Barrier(n_clients)
    lock = threading.Lock()
    # per-client ordered (texts, labels, version, replica) sequences — the
    # per-stream swap-atomicity gate needs request ORDER per client.
    streams: list[list[tuple[list, list, str, str]]] = [
        [] for _ in range(n_clients)
    ]
    errors: list[str] = []

    def counter(name: str) -> int:
        return int(REGISTRY.snapshot()["counters"].get(name, 0))

    def wait_for(pred, timeout_s: float) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if pred():
                return True
            time.sleep(0.02)
        return pred()

    def drive(ci: int) -> None:
        rng = np.random.default_rng(300 + ci)
        client = ServeClient(
            host, port, retry_policy=RetryPolicy(
                max_attempts=8, base_delay_s=0.05, max_delay_s=0.5,
                seed=300 + ci,
            ),
        )

        def one_request(tag: str) -> None:
            lo = int(rng.integers(0, len(docs) - docs_per_req))
            texts = docs[lo:lo + docs_per_req]
            try:
                got, meta = client.detect(texts)
            except (ServeHTTPError, OSError) as e:
                with lock:
                    errors.append(f"client {ci} {tag}: {e}")
                return
            with lock:
                streams[ci].append(
                    (texts, got, meta["version"], meta["replica"])
                )

        for r in range(rounds):
            try:
                barrier.wait(timeout=60)
            except threading.BrokenBarrierError:
                pass
            if ci == 0 and r == kill_round:
                # Replica kill mid-traffic: hammer until the router has
                # observably failed at least one request over (the other
                # clients are mid-round too, so mid-flight failures are
                # also in play).
                fleet.replica(victim).kill()
                for _ in range(30):
                    one_request(f"round {r} (post-kill)")
                    if counter("fleet/failovers") >= 1:
                        break
                continue
            if ci == 0 and r == revive_round:
                # The prober must have ejected the dead replica by now;
                # revive it and wait for the half-open re-admission.
                wait_for(lambda: counter("fleet/ejections") >= 1, 5.0)
                fleet.replica(victim).revive()
                wait_for(
                    lambda: len(fleet.router.eligible()) == 3, 10.0
                )
                continue
            if ci == 0 and r == swap_round:
                client_plain = ServeClient(host, port)
                t0 = time.perf_counter()
                v_new[0] = client_plain.swap(dir_b)
                swap_ms[0] = (time.perf_counter() - t0) * 1e3
                continue
            one_request(f"round {r}")

    threads = [
        threading.Thread(target=drive, args=(ci,)) for ci in range(n_clients)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    final_health = fleet.router.healthz()
    front.stop()
    fleet.close()

    # Parity: every response must match the direct runner of the version
    # that served it — label-exact (argmax), across failovers and the swap.
    checked = mismatches = 0
    versions_served: set[str] = set()
    interleaved_streams = 0
    for ci, stream in enumerate(streams):
        seen_new = False
        for texts, got, version, replica in stream:
            versions_served.add(version)
            runner = runner_a if version == v_old else runner_b
            ids = runner.predict_ids(texts_to_bytes(texts))
            want = [langs[int(i)] for i in ids]
            checked += 1
            if got != want:
                mismatches += 1
            if version == v_new[0]:
                seen_new = True
            elif seen_new:  # old version AFTER the new one: interleaved
                interleaved_streams += 1
                break
    parity = 1.0 if checked and mismatches == 0 else (
        round(1.0 - mismatches / checked, 6) if checked else 0.0
    )

    snap = REGISTRY.snapshot()
    counters = snap["counters"]
    req_h = snap["histograms"].get("fleet/request_s", {})
    answered = sum(len(s) for s in streams)
    result = {
        "smoke_fleet": True,
        "trimmed": trimmed,
        "replicas": 3,
        "clients": n_clients,
        "answered": answered,
        "dropped_responses": len(errors),
        "errors": errors[:5],
        "argmax_parity": parity,
        "failovers": int(counters.get("fleet/failovers", 0)),
        "ejections": int(counters.get("fleet/ejections", 0)),
        "readmissions": int(counters.get("fleet/readmissions", 0)),
        "fleet_sheds": int(counters.get("fleet/shed_requests", 0)),
        "client_retries": int(counters.get("serve/client_retries", 0)),
        "latency_ms": {
            "p50": round(req_h.get("p50", 0.0) * 1e3, 3),
            "p99": round(req_h.get("p99", 0.0) * 1e3, 3),
        },
        "swap": {
            "from": v_old,
            "to": v_new[0],
            "wall_ms": round(swap_ms[0], 3),
            "versions_served": sorted(versions_served),
            "interleaved_streams": interleaved_streams,
        },
        "health": {
            "ready_replicas": final_health["ready_replicas"],
            "pinned_version": final_health["pinned_version"],
        },
        "telemetry": telemetry_block(path),
    }
    result["ok"] = bool(
        not errors
        and parity == 1.0
        and result["failovers"] >= 1
        and result["ejections"] >= 1
        and result["readmissions"] >= 1
        and v_new[0] is not None
        and versions_served == {v_old, v_new[0]}
        and interleaved_streams == 0
        and len(final_health["ready_replicas"]) == 3
    )
    REGISTRY.remove_sink(sink)
    return result


def smoke_storm(jsonl_path: str | None = None, *, trimmed: bool = False) -> dict:
    """CPU-safe storm smoke: the storm-defense stack end to end
    (docs/RESILIENCE.md §7) against a live 3-replica fleet behind the
    router's HTTP front — client -> router -> fleet over real sockets.

    Four scripted legs, each deterministic (manual probe rounds, seeded
    chaos plans, sequential traffic):

    1. **Query of death.** A replica is killed and a poison batch sent
       repeatedly: each send's first dispatch lands on the corpse
       (deterministic least-outstanding/index routing), the router
       records a correlated death against the batch's content signature
       and fails over, and after K=2 deaths the signature is quarantined
       — the third send answers 422 *before* any dispatch and the
       request lands in the serve DLQ. A control batch keeps serving
       throughout. Plus one sub-floor-deadline request, which must 504
       without burning a replica slot.
    2. **Outage under a retry budget.** The replica is killed again with
       a nearly-empty budget (burst=1): the first failover spends the
       only token, the next is *denied* — an explicit budget shed — and
       total dispatches stay within the token-bucket bound
       ``offered * (1 + fraction) + burst``.
    3. **Hedging vs an injected straggler.** The same seeded
       ``fleet/dispatch:delay`` plan runs twice — hedge off, then hedge
       on — so the straggler schedule is identical; the hedged run must
       observably cut p99.
    4. **Overload: hedges self-disable.** Same stragglers plus
       ``serve/admit`` sheds, with a drained budget: every hedge arm is
       denied (``fleet/hedges`` delta must be ZERO) and every request
       either answers or sheds explicitly with a Retry-After.

    Hard gates (``main()`` exits nonzero): >=1 eligible replica at every
    checkpoint, the poison quarantined after exactly K deaths and
    422-rejected thereafter, the amplification bound in leg 2, argmax
    parity exactly 1.0 on every answered request across all legs, p99
    cut in the hedge leg, zero hedges in the overload leg.
    """
    import tempfile

    from spark_languagedetector_tpu import LanguageDetector, Table
    from spark_languagedetector_tpu.ops.encoding import texts_to_bytes
    from spark_languagedetector_tpu.resilience.faults import (
        FaultPlan,
        plan_scope,
    )
    from spark_languagedetector_tpu.resilience.policy import RetryBudget
    from spark_languagedetector_tpu.serve.client import ServeClient, ServeHTTPError
    from spark_languagedetector_tpu.serve.fleet import ServeFleet
    from spark_languagedetector_tpu.serve.quarantine import QuarantineTable
    from spark_languagedetector_tpu.serve.router import RouterServer
    from spark_languagedetector_tpu.telemetry import REGISTRY
    from spark_languagedetector_tpu.telemetry.export import JsonlSink

    REGISTRY.reset()
    path = jsonl_path or os.path.join(
        tempfile.gettempdir(), f"storm_smoke_{os.getpid()}.jsonl"
    )
    sink = JsonlSink(path)
    REGISTRY.add_sink(sink)

    langs = language_names(3)
    docs, labels = make_corpus(langs, 60, mean_len=200, seed=3)
    model = LanguageDetector(langs, [1, 2, 3], 200).fit(
        Table({"lang": labels, "fulltext": docs})
    )
    runner = model._get_runner()
    tmpdir = tempfile.mkdtemp(prefix="storm_smoke_model_")
    model.save(tmpdir + "/m")
    dlq_path = tmpdir + "/quarantine_dlq.jsonl"

    outage_n = 16 if trimmed else 30
    hedge_n = 16 if trimmed else 24
    overload_n = 12 if trimmed else 16
    straggle_s = 0.15 if trimmed else 0.2
    victim = "r0"  # lowest index: the deterministic tie-break sends an
    # idle fleet's first dispatch here, so a killed r0 IS the first hop.

    # Manual probing (start(probe=False) + probe_once()) keeps replica
    # eligibility script-controlled: a killed replica stays "ready" until
    # its dispatch failures eject it, which is exactly the mid-flight
    # death the quarantine correlates.
    fleet = ServeFleet.from_path(
        tmpdir + "/m", replicas=3,
        router_kw=dict(
            probe_interval_ms=40.0, breaker_threshold=2,
            breaker_cooldown_s=0.3, probe_timeout_s=2.0,
            drain_timeout_s=5.0, deadline_floor_ms=5.0,
            retry_budget=RetryBudget(0.2, 10.0, name="storm"),
            quarantine=QuarantineTable(2, dlq_path=dlq_path, name="storm"),
            hedge_enable=False, hedge_quantile=0.05, hedge_min_ms=25.0,
        ),
        max_wait_ms=4, max_rows=64, max_queue_rows=512,
    ).start(probe=False)
    router = fleet.router
    front = RouterServer(router, fleet=fleet, port=0).start()
    host, port = front.address
    client = ServeClient(host, port)

    answered: list[tuple[list, list]] = []  # (texts, labels) for parity
    gates: dict[str, bool] = {}
    survival_checks: list[int] = []

    def counter(name: str) -> int:
        return int(REGISTRY.snapshot()["counters"].get(name, 0))

    def checkpoint() -> None:
        survival_checks.append(len(router.eligible()))

    def ask(texts: list) -> list | None:
        """One /detect; successes feed the parity ledger, sheds and
        rejections return None."""
        try:
            got, _meta = client.detect(texts)
        except (ServeHTTPError, OSError):
            return None
        answered.append((texts, got))
        return got

    def reprobe_all(timeout_s: float = 10.0) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            router.probe_once()
            if len(router.eligible()) == 3:
                return True
            time.sleep(0.05)
        return len(router.eligible()) == 3

    # ---- leg 1: query of death -> quarantine + deadline floor ----------
    poison = [f"query of death {i} ☠ {os.getpid()}" for i in range(4)]
    control = docs[0:4]
    fleet.replica(victim).kill()
    checkpoint()
    ask(poison)   # death 1 on the corpse, failover answers
    ask(poison)   # death 2 -> quarantined (K=2)
    q = router.quarantine.describe()
    gates["poison_quarantined_at_k"] = (
        len(q["quarantined"]) == 1 and q["deaths_threshold"] == 2
    )
    poison_status = 0
    try:
        client.detect(poison)
    except ServeHTTPError as e:
        poison_status = e.status
        gates["poison_422_flagged"] = bool(e.payload.get("quarantined"))
    gates["poison_rejected_422"] = poison_status == 422
    gates["poison_dlq_written"] = (
        os.path.exists(dlq_path) and len(router.quarantine.dlq) >= 1
    )
    gates["control_survives_quarantine"] = ask(control) is not None
    deadline_status = 0
    try:
        client.detect(control, deadline_ms=2.0)  # below the 5ms floor
    except ServeHTTPError as e:
        deadline_status = e.status
    gates["subfloor_deadline_504"] = (
        deadline_status == 504 and counter("fleet/deadline_rejects") >= 1
    )
    checkpoint()
    fleet.replica(victim).revive()
    time.sleep(0.35)  # breaker cooldown before the half-open probe
    gates["victim_readmitted"] = reprobe_all()

    # ---- leg 2: outage under a nearly-empty retry budget ---------------
    router.retry_budget = RetryBudget(0.05, 1.0, name="storm-outage")
    base_dispatch = counter("fleet/dispatches")
    base_shed = counter("fleet/shed_requests")
    base_exhausted = counter("fleet/retry_budget_exhausted")
    fleet.replica(victim).kill()
    checkpoint()
    outage_answered = 0
    for i in range(outage_n):
        lo = (i * 3) % (len(docs) - 3)
        if ask(docs[lo:lo + 3]) is not None:
            outage_answered += 1
    dispatches = counter("fleet/dispatches") - base_dispatch
    amplification = dispatches / outage_n
    # The token-bucket bound: extra attempts <= fraction*successes + burst.
    amp_bound = 1.0 + 0.05 + 1.0 / outage_n + 1e-9
    gates["retry_amplification_bounded"] = amplification <= amp_bound
    gates["budget_shed_observed"] = (
        counter("fleet/shed_requests") - base_shed >= 1
        and counter("fleet/retry_budget_exhausted") - base_exhausted >= 1
    )
    # Exactly one request is budget-shed; everything else must answer.
    gates["outage_goodput_held"] = outage_answered >= outage_n - 1
    checkpoint()
    fleet.replica(victim).revive()
    time.sleep(0.35)
    gates["victim_readmitted_again"] = reprobe_all()

    # ---- leg 3: hedging vs an injected straggler (same schedule 2x) ----
    router.retry_budget = RetryBudget(0.5, 10.0, name="storm-hedge")
    plan = f"seed=11;fleet/dispatch:delay={straggle_s}%0.35"

    def drive_hedge_leg() -> list[float]:
        lats = []
        with plan_scope(FaultPlan.parse(plan)):
            for i in range(hedge_n):
                lo = (i * 2) % (len(docs) - 4)
                t0 = time.perf_counter()
                ask(docs[lo:lo + 4])
                lats.append(time.perf_counter() - t0)
        return lats

    lat_off = drive_hedge_leg()
    router.hedge_enable = True
    base_hedges = counter("fleet/hedges")
    lat_on = drive_hedge_leg()
    hedges = counter("fleet/hedges") - base_hedges
    hedge_wins = counter("fleet/hedge_wins")
    p99_off = float(np.percentile(lat_off, 99))
    p99_on = float(np.percentile(lat_on, 99))
    gates["hedges_fired"] = hedges >= 1 and hedge_wins >= 1
    # Identical straggler schedule (same plan+seed, and hedges inject at
    # fleet/hedge so the primary-side call counter stays aligned): the
    # hedged run must measurably rescue the injected tail.
    gates["hedge_cut_p99"] = (
        p99_off >= straggle_s and p99_on <= 0.75 * p99_off
    )
    checkpoint()

    # ---- leg 4: overload -> hedges self-disable on the drained budget --
    drained = RetryBudget(0.05, 1.0, name="storm-overload")
    drained.try_spend(reason="storm_drain")  # the storm already ate it
    router.retry_budget = drained
    base_hedges = counter("fleet/hedges")
    base_exhausted = counter("fleet/retry_budget_exhausted")
    base_shed = counter("fleet/shed_requests")
    overload_outcomes = []  # "answered" | "shed" | error repr
    with plan_scope(FaultPlan.parse(
        f"seed=13;fleet/dispatch:delay={straggle_s}%0.35;"
        "serve/admit:error%0.3"
    )):
        for i in range(overload_n):
            lo = (i * 5) % (len(docs) - 3)
            try:
                got, _meta = client.detect(docs[lo:lo + 3])
            except ServeHTTPError as e:
                overload_outcomes.append(
                    "shed" if e.status == 503 and e.retry_after_s > 0
                    else f"HTTP {e.status}"
                )
                continue
            except OSError as e:
                overload_outcomes.append(repr(e))
                continue
            answered.append((docs[lo:lo + 3], got))
            overload_outcomes.append("answered")
    gates["overload_zero_hedges"] = (
        counter("fleet/hedges") - base_hedges == 0
    )
    gates["overload_budget_denials"] = (
        counter("fleet/retry_budget_exhausted") - base_exhausted >= 1
    )
    gates["overload_answer_or_shed"] = all(
        o in ("answered", "shed") for o in overload_outcomes
    )
    gates["overload_shed_observed"] = (
        counter("fleet/shed_requests") - base_shed >= 1
    )
    checkpoint()
    gates["fleet_survived"] = min(survival_checks) >= 1 and reprobe_all()

    final_health = router.healthz()
    front.stop()
    fleet.close()

    # Parity: every answered request, every leg, against the direct
    # runner — label-exact (argmax), including hedge-won responses.
    checked = mismatches = 0
    for texts, got in answered:
        ids = runner.predict_ids(texts_to_bytes(texts))
        want = [langs[int(i)] for i in ids]
        checked += 1
        if got != want:
            mismatches += 1
    parity = 1.0 if checked and mismatches == 0 else (
        round(1.0 - mismatches / checked, 6) if checked else 0.0
    )
    gates["argmax_parity"] = parity == 1.0

    failed = sorted(k for k, v in gates.items() if not v)
    result = {
        "smoke_storm": True,
        "trimmed": trimmed,
        "replicas": 3,
        "answered": len(answered),
        "argmax_parity": parity,
        "poison": {
            "status": poison_status,
            "deaths_threshold": 2,
            "quarantined": router.quarantine.describe()["quarantined"],
            "dlq_rows": len(router.quarantine.dlq),
        },
        "outage": {
            "offered": outage_n,
            "answered": outage_answered,
            "dispatches": dispatches,
            "amplification": round(amplification, 4),
            "amplification_bound": round(amp_bound, 4),
        },
        "hedge": {
            "fired": hedges,
            "wins": hedge_wins,
            "p99_off_ms": round(p99_off * 1e3, 3),
            "p99_on_ms": round(p99_on * 1e3, 3),
        },
        "overload": {
            "offered": overload_n,
            "outcomes": {
                o: overload_outcomes.count(o)
                for o in sorted(set(overload_outcomes))
            },
            "hedges": counter("fleet/hedges") - base_hedges,
        },
        "counters": {
            k: counter(k) for k in (
                "fleet/dispatches", "fleet/failovers",
                "fleet/deadline_rejects", "fleet/retry_budget_exhausted",
                "fleet/quarantined_signatures", "fleet/quarantine_rejects",
                "fleet/shed_requests", "fleet/hedges", "fleet/hedge_wins",
            )
        },
        "survival_checks": survival_checks,
        "gates": gates,
        "errors": [f"gate failed: {k}" for k in failed],
        "health": {
            "ready_replicas": final_health["ready_replicas"],
            "retry_budget": final_health["retry_budget"],
            "quarantine": final_health["quarantine"],
            "hedging": final_health["hedging"],
        },
        "telemetry": telemetry_block(path),
    }
    result["ok"] = not failed
    REGISTRY.remove_sink(sink)
    return result


def smoke_scale(jsonl_path: str | None = None, *, trimmed: bool = False) -> dict:
    """CPU-safe elastic-fleet smoke: subprocess replicas + autoscaler
    under a scripted load ramp (docs/SERVING.md §13a).

    Builds a min=1/max=3 :class:`ElasticFleet` of REAL subprocess
    replicas (each loads the persisted model itself, owns its devices,
    serves HTTP) behind the router front, then drives a quiet → burst →
    quiet traffic ramp while the autoscaler ticks. Mid-burst the script
    SIGKILLs one replica subprocess — the supervisor must restart it and
    the router's half-open machinery re-admit it. Child replicas run
    deliberately throttled admission knobs (small dispatch quantum, wide
    flush window) so the burst genuinely saturates one replica's
    measured service rate — the scale-up is driven by the same
    estimated-wait signal production would see, not by a scripted
    override.

    Hard gates (``main()`` exits nonzero): replica count rises under the
    burst AND falls back to the floor after it (>=1 scale-up and >=1
    scale-down observed), at least one supervised subprocess restart,
    zero dropped responses across the ramp, the kill, and every
    membership change, and argmax parity exactly 1.0 against the direct
    runner. ``trimmed=True`` is the tier-1-sized variant (max=2, shorter
    phases, same gates).
    """
    import tempfile
    import threading

    from spark_languagedetector_tpu import LanguageDetector, Table
    from spark_languagedetector_tpu.ops.encoding import texts_to_bytes
    from spark_languagedetector_tpu.resilience.policy import RetryPolicy
    from spark_languagedetector_tpu.scale import Autoscaler, ElasticFleet
    from spark_languagedetector_tpu.serve.client import ServeClient, ServeHTTPError
    from spark_languagedetector_tpu.serve.router import RouterServer
    from spark_languagedetector_tpu.telemetry import REGISTRY
    from spark_languagedetector_tpu.telemetry.export import JsonlSink

    REGISTRY.reset()
    path = jsonl_path or os.path.join(
        tempfile.gettempdir(), f"scale_smoke_{os.getpid()}.jsonl"
    )
    sink = JsonlSink(path)
    REGISTRY.add_sink(sink)

    # Same corpus/model shape as --smoke-fleet: [1,2,3] gram lengths keep
    # every replica on the geometry-stable gather strategy, so argmax
    # parity vs the direct runner is strategy-sound.
    langs = language_names(3)
    docs, labels = make_corpus(langs, 60, mean_len=200, seed=3)
    model = LanguageDetector(langs, [1, 2, 3], 200).fit(
        Table({"lang": labels, "fulltext": docs})
    )
    runner = model._get_runner()
    tmpdir = tempfile.mkdtemp(prefix="scale_smoke_")
    model_dir = os.path.join(tmpdir, "model")
    model.save(model_dir)

    scale_max = 2 if trimmed else 3
    burst_clients = 6 if trimmed else 8
    docs_per_req = 24
    # Throttled children: a 24-row dispatch quantum under a 25 ms flush
    # window and a 48-row admission bound. The burst (clients x 24-row
    # requests) overruns one replica's bound, so it sheds honestly —
    # shed appearance is the autoscaler's pressure signal, the clients'
    # Retry-After backoff absorbs the rejections (zero drops), and the
    # pressure clears only when added replicas spread the load.
    child_env = {
        "LANGDETECT_SERVE_MAX_ROWS": "24",
        "LANGDETECT_SERVE_MAX_WAIT_MS": "25",
        "LANGDETECT_SERVE_QUEUE_ROWS": "48",
    }
    fleet = ElasticFleet(
        model_dir, replicas=1,
        fleet_name=f"smoke_scale_{os.getpid()}",
        pidfile_dir=os.path.join(tmpdir, "pids"),
        child_env=child_env,
        # Warm founders, cold joiners: the floor replica is genuinely
        # ready (compiled) before traffic starts, while scale-up
        # replicas fold their compile into the first dispatch instead
        # of the spawn latency the autoscaler waits out — a cold
        # joiner's slow first batch is honest elastic-capacity behavior
        # the clients' Retry-After backoff absorbs.
        prewarm=True, joiner_prewarm=False,
        router_kw=dict(
            probe_interval_ms=40.0, breaker_threshold=2,
            breaker_cooldown_s=0.3, probe_timeout_s=2.0,
            drain_timeout_s=5.0,
        ),
    ).start()
    scaler = Autoscaler(
        fleet, scale_min=1, scale_max=scale_max, interval_ms=100.0,
        up_ticks=2, down_ticks=4, pressure_wait_ms=30.0,
        idle_rows_per_s=20.0,
    ).start()
    front = RouterServer(fleet.router, port=0).start()
    host, port = front.address

    lock = threading.Lock()
    responses: list[tuple[list, list]] = []
    errors: list[str] = []
    live_samples: dict[str, list[int]] = {
        "quiet1": [], "burst": [], "quiet2": [],
    }
    phase = ["quiet1"]
    stop = threading.Event()

    def drive(ci: int) -> None:
        rng = np.random.default_rng(700 + ci)
        client = ServeClient(
            host, port, retry_policy=RetryPolicy(
                # Wide budget: a cold joiner's first-dispatch compile can
                # stall the whole fleet for a few seconds mid-burst; the
                # clients must out-wait it, never drop.
                max_attempts=30, base_delay_s=0.05, max_delay_s=0.5,
                seed=700 + ci,
            ),
        )
        while not stop.is_set():
            current = phase[0]
            if current == "quiet2" or (current == "quiet1" and ci > 0):
                # Burst clients idle outside the burst; client 0 keeps a
                # light pulse through quiet1 only — quiet2 is true
                # silence so the arrival EMA decays to the floor.
                time.sleep(0.05)
                continue
            n = docs_per_req if current == "burst" else 2
            lo = int(rng.integers(0, len(docs) - n + 1))
            texts = docs[lo:lo + n]
            try:
                got, _meta = client.detect(texts)
            except (ServeHTTPError, OSError) as e:
                with lock:
                    errors.append(f"client {ci} [{current}]: {e}")
                continue
            with lock:
                responses.append((texts, got))
            if current == "quiet1":
                time.sleep(0.04)

    threads = [
        threading.Thread(target=drive, args=(ci,))
        for ci in range(burst_clients)
    ]
    for t in threads:
        t.start()

    def sample_phase(name: str, seconds: float) -> None:
        deadline = time.monotonic() + seconds
        while time.monotonic() < deadline:
            live_samples[name].append(fleet.live_count())
            time.sleep(0.1)

    def counter(name: str) -> int:
        return int(REGISTRY.snapshot()["counters"].get(name, 0))

    def wait_for(pred, timeout_s: float) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if pred():
                return True
            time.sleep(0.05)
        return pred()

    restart_drilled = [False]
    try:
        sample_phase("quiet1", 1.5 if trimmed else 3.0)
        phase[0] = "burst"
        # Burst until the autoscaler has demonstrably scaled up, then
        # keep the pressure on while the kill drill runs.
        wait_for(lambda: counter("scale/ups") >= 1, 60.0)
        live_samples["burst"].append(fleet.live_count())
        if counter("scale/ups") >= 1:
            # SIGKILL the newest replica mid-burst: the supervisor must
            # restart it on its pinned port and the router re-admit it.
            # (_newest_member walks the member table under the
            # supervisor's lock — the autoscaler thread may be admitting
            # another member at this very moment.)
            victim = fleet._newest_member()
            rep = fleet.supervisor.members[victim]
            before = counter("scale/restarts")
            rep.proc.kill()
            restart_drilled[0] = wait_for(
                lambda: counter("scale/restarts") > before
                and rep.alive, 90.0,
            )
            wait_for(
                lambda: victim in fleet.router.eligible(), 15.0
            )
        sample_phase("burst", 1.0 if trimmed else 2.5)
        phase[0] = "quiet2"
        # True silence: the arrival EMA decays, the idle cooldown
        # elapses, and the fleet walks back down to the floor.
        wait_for(
            lambda: counter("scale/downs") >= 1
            and fleet.live_count() == 1,
            90.0,
        )
        sample_phase("quiet2", 0.5)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=60)
        scaler.close()
        final_health = fleet.healthz()
        front.stop()
        fleet.close()

    # Parity: single model version throughout — every response must be
    # label-exact against the direct runner, across replicas, the
    # restart, and every membership change.
    checked = mismatches = 0
    for texts, got in responses:
        ids = runner.predict_ids(texts_to_bytes(texts))
        want = [langs[int(i)] for i in ids]
        checked += 1
        if got != want:
            mismatches += 1
    parity = 1.0 if checked and mismatches == 0 else (
        round(1.0 - mismatches / checked, 6) if checked else 0.0
    )

    snap = REGISTRY.snapshot()
    counters = snap["counters"]
    peak_burst = max(live_samples["burst"] or [0])
    end_quiet2 = (live_samples["quiet2"] or [0])[-1]
    result = {
        "smoke_scale": True,
        "trimmed": trimmed,
        "scale_min": 1,
        "scale_max": scale_max,
        "answered": len(responses),
        "dropped_responses": len(errors),
        "errors": errors[:5],
        "argmax_parity": parity,
        "scale_ups": int(counters.get("scale/ups", 0)),
        "scale_downs": int(counters.get("scale/downs", 0)),
        "supervised_restarts": int(counters.get("scale/restarts", 0)),
        "spawn_failures": int(counters.get("scale/spawn_failures", 0)),
        "failovers": int(counters.get("fleet/failovers", 0)),
        "client_retries": int(counters.get("serve/client_retries", 0)),
        "replica_timeline": {
            "quiet1_max": max(live_samples["quiet1"] or [0]),
            "burst_peak": peak_burst,
            "quiet2_end": end_quiet2,
        },
        "restart_drilled": restart_drilled[0],
        "health": {
            "ready_replicas": final_health["ready_replicas"],
            "target_replicas": final_health["target_replicas"],
        },
        "telemetry": telemetry_block(path),
    }
    result["ok"] = bool(
        not errors
        and parity == 1.0
        and result["scale_ups"] >= 1
        and result["scale_downs"] >= 1
        and result["supervised_restarts"] >= 1
        and restart_drilled[0]
        and max(live_samples["quiet1"] or [0]) == 1
        and peak_burst >= 2
        and end_quiet2 == 1
    )
    REGISTRY.remove_sink(sink)
    return result


def smoke_spawn(jsonl_path: str | None = None, *, trimmed: bool = False) -> dict:
    """CPU-safe cold-start-plane smoke: the prewarm handshake end to end
    (docs/PERFORMANCE.md §12, docs/SERVING.md §13b–13c).

    Bakes the mmap artifact for a persisted model, then spawns the SAME
    replica twice through a :class:`ReplicaSupervisor` that ships the
    handshake — baked-artifact path, tuning profile, and a persistent
    compile-cache dir that starts empty. The first (cold) spawn fills
    the cache; the second (warm) spawn must ride it. Both spawns report
    the child-measured warmup span (model load + lattice prewarm — the
    READY line carries it, imports excluded so the ~constant interpreter
    start cost doesn't dilute the signal) and the coordinator-measured
    spawn-to-READY wall, and both take their FIRST post-READY dispatch
    checked label-exact against the direct runner.

    The cold spawn traces the full lattice (every program an observed
    ``compile_cache/misses``) and earns the cache's signature manifest;
    the warm spawn must take the verified-warm fast path — one sentinel
    dispatch proving an actual ``compile_cache/hits`` delta, the rest of
    the lattice deferred to bounded trace+hit on first touch
    (docs/PERFORMANCE.md §12).

    Hard gates (``main()`` exits nonzero): warm warmup at least
    ``min_ratio`` times faster than cold (3.0 full / 1.5 trimmed — the
    trimmed bound is deliberately loose so tier-1 stays robust on hosts
    whose compile floor differs), the warm child's prewarm ran in
    ``sentinel`` mode, ``compile_cache/hits`` > 0 in the warm child's
    own counters (cache traffic observed, not inferred from wall time),
    the baked loader actually used on BOTH spawns
    (``artifacts/baked_loads`` >= 1 — a silent parquet fallback would
    still pass the ratio gate, masking a cold-load regression),
    ``scale/spawn_failures`` == 0, and argmax parity exactly 1.0 from
    the first dispatch on both spawns. The full variant densifies the
    bucket lattice through the shipped tuning profile (16 buckets) —
    exercising the handshake's profile leg and widening the cold side
    the way a many-geometry deployment would see it.
    """
    import tempfile

    from spark_languagedetector_tpu import LanguageDetector, Table
    from spark_languagedetector_tpu.artifacts.bake import (
        artifact_path_for, bake_model,
    )
    from spark_languagedetector_tpu.exec.profile import TuningProfile
    from spark_languagedetector_tpu.ops.encoding import texts_to_bytes
    from spark_languagedetector_tpu.resilience.policy import RetryPolicy
    from spark_languagedetector_tpu.scale.replica import ReplicaSupervisor
    from spark_languagedetector_tpu.serve.client import ServeClient
    from spark_languagedetector_tpu.telemetry import REGISTRY
    from spark_languagedetector_tpu.telemetry.export import JsonlSink

    REGISTRY.reset()
    path = jsonl_path or os.path.join(
        tempfile.gettempdir(), f"spawn_smoke_{os.getpid()}.jsonl"
    )
    sink = JsonlSink(path)
    REGISTRY.add_sink(sink)

    # Same corpus/model shape as --smoke-scale: [1,2,3] gram lengths keep
    # the child on the geometry-stable gather strategy, so first-dispatch
    # parity vs the direct runner is strategy-sound.
    langs = language_names(3)
    docs, labels = make_corpus(langs, 60, mean_len=200, seed=3)
    model = LanguageDetector(langs, [1, 2, 3], 200).fit(
        Table({"lang": labels, "fulltext": docs})
    )
    runner = model._get_runner()
    tmpdir = tempfile.mkdtemp(prefix="spawn_smoke_")
    model_dir = os.path.join(tmpdir, "model")
    model.save(model_dir)
    baked_path = bake_model(model, artifact_path_for(model_dir))

    # The warm/cold contrast the gate measures is per-program compile
    # cost; the fixed spawn overheads (backend init, model load) sit in
    # both numerators. The full variant ships a denser bucket lattice
    # through the handshake's tuning profile so the per-program term
    # dominates — the same lever a real deployment with many geometries
    # pulls. Trimmed keeps the default lattice (tier-1 wall time).
    profile_path = None
    if not trimmed:
        profile_path = os.path.join(tmpdir, "tuning.json")
        TuningProfile(
            tuned={"length_buckets": [128 * i for i in range(1, 17)]},
            source={"suite": "smoke_spawn"},
        ).save(profile_path)

    cache_dir = os.path.join(tmpdir, "compile_cache")
    os.makedirs(cache_dir, exist_ok=True)
    metrics_dir = os.path.join(tmpdir, "metrics")
    sup = ReplicaSupervisor(
        model_dir,
        fleet_name=f"smoke_spawn_{os.getpid()}",
        pidfile_dir=os.path.join(tmpdir, "pids"),
        metrics_dir=metrics_dir,
        compile_cache_dir=cache_dir,
        tuning_profile=profile_path,
    )

    min_ratio = 1.5 if trimmed else 3.0
    probe = docs[:24]
    want_ids = runner.predict_ids(texts_to_bytes(probe))
    want = [langs[int(i)] for i in want_ids]
    # The dispatch above kicked off the coordinator's background roofline
    # gauges; on a small host that thread would steal cycles from the
    # cold child and skew the ratio — wait it out before spawning.
    cost_thread = getattr(runner, "_cost_thread", None)
    if cost_thread is not None:
        cost_thread.join(timeout=120)

    def child_counters(name: str) -> dict:
        """The child's terminal ``telemetry.snapshot`` counters — its
        drain path flushes one after the last answered request."""
        counters: dict = {}
        fpath = os.path.join(metrics_dir, f"replica-{name}.jsonl")
        try:
            with open(fpath, "r", encoding="utf-8") as f:
                for line in f:
                    try:
                        ev = json.loads(line)
                    except ValueError:
                        continue
                    if ev.get("event") == "telemetry.snapshot":
                        counters = ev.get("counters", {})
        except OSError:
            pass
        return counters

    def one_spawn(name: str) -> dict:
        rep = sup.spawn(name)
        client = ServeClient(
            *rep.address,
            retry_policy=RetryPolicy(
                max_attempts=5, base_delay_s=0.05, max_delay_s=0.5, seed=7
            ),
        )
        got, _meta = client.detect(probe)
        sup.stop(name)
        counters = child_counters(name)
        return {
            "spawn_ready_s": round(rep.last_spawn_ready_s or 0.0, 4),
            "warmup_s": round(rep.last_warmup_s or 0.0, 4),
            "prewarm_mode": rep.last_prewarm_mode,
            "first_dispatch_parity": 1.0 if got == want else 0.0,
            "compile_cache_hits": int(counters.get("compile_cache/hits", 0)),
            "compile_cache_misses": int(
                counters.get("compile_cache/misses", 0)
            ),
            "baked_loads": int(counters.get("artifacts/baked_loads", 0)),
        }

    errors: list[str] = []
    cold = warm = None
    try:
        cold = one_spawn("cold0")
        warm = one_spawn("warm0")
    except Exception as e:  # SpawnError, ServeHTTPError, OSError
        errors.append(f"{type(e).__name__}: {e}")
    finally:
        sup.close()

    cold = cold or {}
    warm = warm or {}
    warmup_ratio = (
        round(cold["warmup_s"] / warm["warmup_s"], 3)
        if cold.get("warmup_s") and warm.get("warmup_s") else 0.0
    )
    spawn_failures = int(
        REGISTRY.snapshot()["counters"].get("scale/spawn_failures", 0)
    )
    result = {
        "smoke_spawn": True,
        "trimmed": trimmed,
        "artifact": baked_path,
        "lattice_buckets": 16 if profile_path else None,
        "errors": errors,
        "cold": cold,
        "warm": warm,
        "warmup_ratio": warmup_ratio,
        "min_ratio": min_ratio,
        "spawn_failures": spawn_failures,
        "telemetry": telemetry_block(path),
    }
    result["ok"] = bool(
        not errors
        and warmup_ratio >= min_ratio
        and cold.get("prewarm_mode") == "full"
        and warm.get("prewarm_mode") == "sentinel"
        and warm.get("compile_cache_hits", 0) > 0
        and cold.get("baked_loads", 0) >= 1
        and warm.get("baked_loads", 0) >= 1
        and spawn_failures == 0
        and cold.get("first_dispatch_parity") == 1.0
        and warm.get("first_dispatch_parity") == 1.0
    )
    REGISTRY.remove_sink(sink)
    return result


def smoke_obs(jsonl_path: str | None = None, *, trimmed: bool = False) -> dict:
    """CPU-safe fleet-observability smoke: the whole plane of
    docs/OBSERVABILITY.md §14–15 under one gate.

    Runs a 2-replica subprocess fleet — each worker capturing its own
    JSONL via ``--metrics-jsonl`` while the coordinator captures its own
    sink — with the fleet collector and SLO evaluator riding the
    autoscaler ticks, drives concurrent traffic through one induced shed
    burst and a trailing silence (which walks the fleet down one
    replica), then audits the plane end to end:

      * **aggregate exactness** — the collector's merged counters equal
        the sum of its per-replica views plus the coordinator's own
        registry, exactly, INCLUDING the scale-down victim's retained
        terminal scrape;
      * **stitched nesting** — the router capture plus every
        ``replica-*.jsonl`` stitch into one Perfetto timeline, and at
        least one request flow crosses processes (router
        ``fleet/dispatch`` → replica ``serve/dispatch`` → runner
        ``score``) sharing one ``trace_id`` with non-negative duration
        slack (a child span never out-lasts its real-time parent);
      * **burn-rate trip-and-clear** — the availability objective alerts
        during the shed burst (``slo/alerts`` >= 1, a
        ``slo_availability_burn`` reason on the fleet ``/healthz``) and
        is clear again after the silence;
      * **zero scrape failures** — ``fleet/agg_scrape_failures`` == 0
        across every round including the terminal scrape;

    plus the serving invariants every smoke holds: zero dropped
    responses, argmax parity exactly 1.0 against the direct runner, a
    ``server_timing``/``server`` identity block on the responses, and
    >= 1 autoscaler scale-down. ``trimmed=True`` is the tier-1-sized
    variant (shorter phases, same gates).
    """
    import glob as globmod
    import tempfile
    import threading

    from spark_languagedetector_tpu import LanguageDetector, Table
    from spark_languagedetector_tpu.ops.encoding import texts_to_bytes
    from spark_languagedetector_tpu.resilience.policy import RetryPolicy
    from spark_languagedetector_tpu.scale import Autoscaler, ElasticFleet
    from spark_languagedetector_tpu.serve.client import ServeClient, ServeHTTPError
    from spark_languagedetector_tpu.serve.router import RouterServer
    from spark_languagedetector_tpu.telemetry import REGISTRY
    from spark_languagedetector_tpu.telemetry.export import JsonlSink
    from spark_languagedetector_tpu.telemetry.slo import (
        SloEvaluator,
        default_objectives,
    )
    from spark_languagedetector_tpu.telemetry.stitch import (
        load_captures,
        nesting_slack_s,
        trace_flows,
        write_stitched_trace,
    )

    REGISTRY.reset()
    path = jsonl_path or os.path.join(
        tempfile.gettempdir(), f"obs_smoke_{os.getpid()}.jsonl"
    )
    sink = JsonlSink(path)
    REGISTRY.add_sink(sink)

    langs = language_names(3)
    docs, labels = make_corpus(langs, 60, mean_len=200, seed=3)
    model = LanguageDetector(langs, [1, 2, 3], 200).fit(
        Table({"lang": labels, "fulltext": docs})
    )
    runner = model._get_runner()
    tmpdir = tempfile.mkdtemp(prefix="obs_smoke_")
    model_dir = os.path.join(tmpdir, "model")
    model.save(model_dir)
    metrics_dir = os.path.join(tmpdir, "metrics")

    burst_clients = 6
    docs_per_req = 24
    # Same throttled-admission children as --smoke-scale: the burst
    # (clients x 24-row requests against two 48-row bounds) overruns the
    # fleet honestly, which is what burns the availability objective.
    child_env = {
        "LANGDETECT_SERVE_MAX_ROWS": "24",
        "LANGDETECT_SERVE_MAX_WAIT_MS": "25",
        "LANGDETECT_SERVE_QUEUE_ROWS": "48",
    }
    # Smoke-sized SLO windows (seconds, not minutes) so the trip AND the
    # clear both happen inside the phase script; the latency objective's
    # threshold sits above the client timeout so only availability (the
    # induced signal) can alert.
    slo = SloEvaluator(
        default_objectives(latency_p99_ms=60_000.0),
        short_window_s=1.5, long_window_s=4.0,
    )
    fleet = ElasticFleet(
        model_dir, replicas=2,
        fleet_name=f"smoke_obs_{os.getpid()}",
        pidfile_dir=os.path.join(tmpdir, "pids"),
        child_env=child_env,
        metrics_dir=metrics_dir,
        slo=slo,
        prewarm=True, joiner_prewarm=False,
        router_kw=dict(
            probe_interval_ms=40.0, breaker_threshold=2,
            breaker_cooldown_s=0.3, probe_timeout_s=2.0,
            drain_timeout_s=5.0,
        ),
    ).start()
    scaler = Autoscaler(
        fleet, scale_min=1, scale_max=2, interval_ms=100.0,
        up_ticks=2, down_ticks=4, pressure_wait_ms=30.0,
        idle_rows_per_s=20.0,
    ).start()
    front = RouterServer(
        fleet.router, port=0, collector=fleet.collector, slo=fleet.slo
    ).start()
    host, port = front.address

    lock = threading.Lock()
    responses: list[tuple[list, list]] = []
    errors: list[str] = []
    meta_sample: dict = {}
    phase = ["quiet1"]
    stop = threading.Event()

    def drive(ci: int) -> None:
        rng = np.random.default_rng(800 + ci)
        client = ServeClient(
            host, port, retry_policy=RetryPolicy(
                max_attempts=30, base_delay_s=0.05, max_delay_s=0.5,
                seed=800 + ci,
            ),
        )
        while not stop.is_set():
            current = phase[0]
            if current == "quiet2" or (current == "quiet1" and ci > 0):
                # Burst clients idle outside the burst; client 0 keeps a
                # light uncoalesced pulse through quiet1 (clean stitched
                # flows) — quiet2 is true silence so the arrival EMA
                # decays and the short SLO window drains.
                time.sleep(0.05)
                continue
            n = docs_per_req if current == "burst" else 2
            lo = int(rng.integers(0, len(docs) - n + 1))
            texts = docs[lo:lo + n]
            try:
                got, meta = client.detect(texts)
            except (ServeHTTPError, OSError) as e:
                with lock:
                    errors.append(f"client {ci} [{current}]: {e}")
                continue
            with lock:
                responses.append((texts, got))
                if not meta_sample and meta.get("server_timing"):
                    meta_sample.update({
                        "server_timing": meta.get("server_timing"),
                        "server": meta.get("server"),
                    })
            if current == "quiet1":
                time.sleep(0.04)

    threads = [
        threading.Thread(target=drive, args=(ci,))
        for ci in range(burst_clients)
    ]
    for t in threads:
        t.start()

    def counter(name: str) -> int:
        return int(REGISTRY.snapshot()["counters"].get(name, 0))

    def wait_for(pred, timeout_s: float) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if pred():
                return True
            time.sleep(0.05)
        return pred()

    burn_reasons: list[str] = []
    burn_tripped = burn_cleared = scaled_down = False
    try:
        time.sleep(1.0 if trimmed else 2.0)
        phase[0] = "burst"
        # Burst until the availability objective demonstrably alerts —
        # sheds are the induced error budget burn.
        burn_tripped = wait_for(lambda: counter("slo/alerts") >= 1, 60.0)
        if burn_tripped:
            burn_reasons = list(fleet.healthz().get("reasons") or [])
        time.sleep(0.3)
        phase[0] = "quiet2"
        # Silence: the short window drains (the alert clears), the
        # arrival EMA decays, and the fleet walks down one replica —
        # whose terminal scrape the collector must retain.
        burn_cleared = wait_for(lambda: not fleet.slo.burning(), 30.0)
        scaled_down = wait_for(
            lambda: counter("scale/downs") >= 1
            and fleet.live_count() == 1,
            90.0,
        )
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=60)
        scaler.close()
        final_health = fleet.healthz()
        front.stop()
        fleet.close()

    # Parity: every response label-exact against the direct runner,
    # across both replicas and the scale-down.
    checked = mismatches = 0
    for texts, got in responses:
        ids = runner.predict_ids(texts_to_bytes(texts))
        want = [langs[int(i)] for i in ids]
        checked += 1
        if got != want:
            mismatches += 1
    parity = 1.0 if checked and mismatches == 0 else (
        round(1.0 - mismatches / checked, 6) if checked else 0.0
    )

    # Gate: aggregate ≡ per-replica views + the coordinator's registry,
    # exactly. Everything is quiescent post-close, so both reads see the
    # same counters; the retained (drained) member must participate.
    agg_counters = fleet.collector.aggregate()["counters"]
    per = fleet.collector.per_replica()
    local = REGISTRY.mergeable_snapshot()["counters"]
    expect: dict[str, float] = {}
    for view in per.values():
        for cname, val in view["counters"].items():
            expect[cname] = expect.get(cname, 0) + val
    for cname, val in local.items():
        expect[cname] = expect.get(cname, 0) + val
    aggregate_exact = set(expect) == set(agg_counters) and all(
        expect[cname] == agg_counters[cname] for cname in expect
    )
    retained = [
        name for name, view in per.items()
        if view["state"] == "retired"
        and sum(view["counters"].values()) > 0
    ]

    # Gate: stitched timeline + a complete cross-process request flow
    # with non-negative nesting slack. A flow with a slack at all has
    # router+replica+runner spans under ONE trace_id, and those spans
    # can only come from different captures.
    replica_logs = sorted(
        globmod.glob(os.path.join(metrics_dir, "replica-*.jsonl"))
    )
    stitched_path = write_stitched_trace(
        [path] + replica_logs,
        os.path.join(tmpdir, "stitched.trace.json"),
    )
    flows = trace_flows(load_captures([path] + replica_logs))
    cross_flows = 0
    best_slack: float | None = None
    for spans in flows.values():
        if len({s["process"] for s in spans}) > 1:
            cross_flows += 1
        slack = nesting_slack_s(spans)
        if slack is not None and (best_slack is None or slack > best_slack):
            best_slack = slack

    snap = REGISTRY.snapshot()
    counters = snap["counters"]
    result = {
        "smoke_obs": True,
        "trimmed": trimmed,
        "replicas": 2,
        "answered": len(responses),
        "dropped_responses": len(errors),
        "errors": errors[:5],
        "argmax_parity": parity,
        "server_timing_sample": meta_sample.get("server_timing"),
        "server_identity_sample": meta_sample.get("server"),
        "slo_alerts": int(counters.get("slo/alerts", 0)),
        "burn_reasons": burn_reasons,
        "burn_cleared": burn_cleared,
        "final_burning": bool(final_health["slo"]["burning"]),
        "scale_downs": int(counters.get("scale/downs", 0)),
        "scaled_down": scaled_down,
        "agg_scrapes": int(counters.get("fleet/agg_scrapes", 0)),
        "agg_scrape_failures": int(
            counters.get("fleet/agg_scrape_failures", 0)
        ),
        "aggregate_exact": aggregate_exact,
        "aggregate_counter_names": len(agg_counters),
        "retained_members": retained,
        "replica_captures": [os.path.basename(p) for p in replica_logs],
        "stitched_trace": stitched_path,
        "trace_flows": len(flows),
        "cross_process_flows": cross_flows,
        "nesting_slack_s": best_slack,
        "telemetry": telemetry_block(path),
    }
    result["ok"] = bool(
        not errors
        and parity == 1.0
        and meta_sample.get("server_timing") is not None
        and (meta_sample.get("server") or {}).get("replica") is not None
        and burn_tripped
        and result["slo_alerts"] >= 1
        and "slo_availability_burn" in burn_reasons
        and burn_cleared
        and not result["final_burning"]
        and scaled_down
        and result["scale_downs"] >= 1
        and result["agg_scrapes"] >= 1
        and result["agg_scrape_failures"] == 0
        and aggregate_exact
        and retained
        and len(replica_logs) >= 2
        and cross_flows >= 1
        and best_slack is not None
        and best_slack >= 0.0
    )
    REGISTRY.remove_sink(sink)
    return result


def smoke_refit(jsonl_path: str | None = None) -> dict:
    """CPU-safe continuous-learning smoke: the full data-in → model-out →
    serving loop under one gate (ROADMAP item 2).

    Drives a labeled micro-batch stream through the incremental refit
    engine: streaming accumulator updates via the pipelined count path,
    per-batch crash-atomic checkpoints, a mid-stream kill + resume from
    the persisted accumulator (the resume token rides inside the state),
    periodic refits that re-run only the on-device finalize, and every
    refit hot-swapped into a live ``serve.ModelRegistry``.

    Hard gates (``main()`` exits nonzero): the final served profile must
    be BIT-IDENTICAL (ids and float64 weights) to a from-scratch
    ``fit`` over the concatenation of every streamed batch; the resumed
    run must actually fast-forward (``resumed_from > 0``) and re-count
    nothing; the registry must serve the last refit with its refit token
    in the swap metadata; and the finalize collect must move only winner
    rows (``collect.ratio`` well under 1 — the §8 fit-collect-wall
    contract, also enforced capture-over-capture by the compare guard's
    ``langdetect_fit_collect_bytes`` tracking).
    """
    import shutil
    import tempfile

    from spark_languagedetector_tpu import LanguageDetector, Table
    from spark_languagedetector_tpu.serve import ModelRegistry
    from spark_languagedetector_tpu.stream import AutoRefit
    from spark_languagedetector_tpu.telemetry import REGISTRY
    from spark_languagedetector_tpu.telemetry.export import JsonlSink

    REGISTRY.reset()
    path = jsonl_path or os.path.join(
        tempfile.gettempdir(), f"refit_smoke_{os.getpid()}.jsonl"
    )
    sink = JsonlSink(path)
    REGISTRY.add_sink(sink)
    tmpdir = tempfile.mkdtemp(prefix="refit_smoke_")
    state_path = os.path.join(tmpdir, "fit_state")

    langs = language_names(3)
    docs, labels = make_corpus(langs, 120, mean_len=200, seed=5)
    batch_rows = 12
    batches = [
        Table({"lang": labels[lo:lo + batch_rows],
               "fulltext": docs[lo:lo + batch_rows]})
        for lo in range(0, len(docs), batch_rows)
    ]

    def det():
        return (
            LanguageDetector(langs, [1, 2], 300)
            .set_vocab_mode("hashed")
            .set_hash_bits(12)
            .set_fit_backend("device")
        )

    errors: list[str] = []
    try:
        registry = ModelRegistry(drain_timeout_s=2.0)
        # Phase 1: stream the first 4 batches with per-batch checkpoints
        # and a refit+hot-swap every 2 — then stop (the simulated kill:
        # the process state is discarded, only the checkpoint survives).
        first = AutoRefit(
            det(), registry, state_path=state_path,
            refit_every_batches=2, final_refit=False,
        )
        first.run(batches, max_batches=4)
        phase1_refits = first.progress.refits
        phase1_version = first.progress.last_version
        del first

        # Phase 2: a fresh driver on the same state resumes past the 4
        # committed batches (re-counting nothing) and streams the rest.
        second = AutoRefit(
            det(), registry, state_path=state_path, refit_every_batches=3,
        )
        progress = second.run(batches)
        resumed_from = progress.resumed_from

        # From-scratch oracle over the concatenated corpus.
        scratch = det().fit(
            Table({"lang": labels, "fulltext": docs})
        )
        served = registry.peek()
        served_profile = served.model.profile
        ids_ok = np.array_equal(served_profile.ids, scratch.profile.ids)
        weights_ok = ids_ok and np.array_equal(
            served_profile.weights, scratch.profile.weights
        )
        if not weights_ok:
            errors.append("refit profile != from-scratch fit (bit-exact)")
        if resumed_from != 4:
            errors.append(f"resume fast-forwarded {resumed_from} != 4")
        meta = served.describe().get("metadata") or {}
        if meta.get("refit_token") != len(batches):
            errors.append(
                f"served refit_token {meta.get('refit_token')} != "
                f"{len(batches)}"
            )

        snap = REGISTRY.snapshot()
        collect_bytes = snap["counters"].get("fit/collect_bytes", 0)
        spec = det()._vocab_spec()
        table_bytes = spec.id_space_size * len(langs) * 4
        finalizes = max(phase1_refits + progress.refits + 1, 1)  # + scratch
        per_fit = collect_bytes / finalizes
        ratio = per_fit / table_bytes
        if not ratio < 0.5:
            errors.append(
                f"collect moved {per_fit:.0f}B/fit vs {table_bytes}B table "
                "— winner-rows-only collect regressed"
            )

        result = {
            "smoke_refit": True,
            "batches": len(batches),
            "docs": len(docs),
            "refits": phase1_refits + progress.refits,
            "resumed_from": resumed_from,
            "versions": [v["version"] for v in registry.versions()],
            "served_version": served.version,
            "phase1_version": phase1_version,
            "refit_token": meta.get("refit_token"),
            "parity_ok": weights_ok,
            "collect": {
                "bytes_per_fit": round(per_fit, 1),
                "full_table_bytes": table_bytes,
                "ratio": round(ratio, 6),
            },
            "errors": errors[:5],
            "telemetry": telemetry_block(path),
        }
        result["ok"] = not errors
        return result
    finally:
        REGISTRY.remove_sink(sink)
        shutil.rmtree(tmpdir, ignore_errors=True)


def smoke_tune(jsonl_path: str | None = None) -> dict:
    """CPU-safe autotuner smoke: capture → ``exec.tune`` → re-run tuned.

    The full measured-defaults loop on a config-1-shaped model (bigram
    exact vocab, 3 languages): pass A scores a corpus whose length
    distribution deliberately misaligns with the default bucket lattice
    (the everyday padding tax) under untuned defaults with a JSONL
    capture; the autotuner replays the capture and emits a versioned
    tuning profile; pass B points ``LANGDETECT_TUNING_PROFILE`` at it and
    re-scores the same docs on a freshly-constructed runner — the real
    startup-load path, no special plumbing.

    Hard gates (``main()`` exits nonzero): aggregate padding waste
    (1 − real/capacity wire bytes, the exact whole-run counters) strictly
    lower under the tuned profile, argmax parity exactly 1.0 vs the
    untuned pass (gather strategy — batch-geometry-stable, so scores are
    bit-identical across lattices by construction and any parity miss is
    a real bug), and the tuned lattice within the default compile-shape
    budget. Seconds, no accelerator.
    """
    import tempfile

    from spark_languagedetector_tpu import LanguageDetector, Table
    from spark_languagedetector_tpu.api.runner import BatchRunner
    from spark_languagedetector_tpu.exec import config as exec_config
    from spark_languagedetector_tpu.exec import tune as exec_tune
    from spark_languagedetector_tpu.ops.encoding import (
        DEFAULT_LENGTH_BUCKETS,
        texts_to_bytes,
    )
    from spark_languagedetector_tpu.telemetry import REGISTRY
    from spark_languagedetector_tpu.telemetry.export import JsonlSink
    from spark_languagedetector_tpu.telemetry.report import load_events

    langs = language_names(3)
    train_docs, train_labels = make_corpus(langs, 90, mean_len=200, seed=3)
    model = LanguageDetector(langs, [2], 2000).fit(
        Table({"lang": train_labels, "fulltext": train_docs})
    )
    # Eval lengths clustered just past bucket edges: the bulk lands in
    # (256, 512] (padded to 512 at ~0.6 fill) with a short-doc minority in
    # (64, 128] — the distribution shape the DP solver exists for.
    docs_a, _ = make_corpus(langs, 600, seed=5, len_range=(260, 380))
    docs_b, _ = make_corpus(langs, 200, seed=7, len_range=(80, 120))
    eval_docs = texts_to_bytes(docs_a + docs_b)

    weights, lut, cuckoo = model.profile.device_membership()

    def build_runner() -> BatchRunner:
        # gather = the geometry-stable A/B reference; padded transfers
        # (no ragged) so the padded lattice is what the gate measures.
        return BatchRunner(
            weights=weights, lut=lut, cuckoo=cuckoo,
            spec=model.profile.spec, strategy="gather",
            ragged_transfer=False,
        )

    def one_pass(sink_path: str) -> tuple:
        sink = JsonlSink(sink_path)
        REGISTRY.reset()
        REGISTRY.add_sink(sink)
        try:
            runner = build_runner()
            ids = runner.predict_ids(eval_docs)
            REGISTRY.flush()  # snapshot (exec/len + wire counters) → jsonl
            snap = REGISTRY.snapshot()
            real = snap["counters"].get("score/real_bytes", 0)
            cap = snap["counters"].get("score/capacity_bytes", 0)
            waste = 1.0 - real / cap if cap else 0.0
            return ids, waste, tuple(runner.length_buckets)
        finally:
            REGISTRY.remove_sink(sink)

    path_a = jsonl_path or os.path.join(
        tempfile.gettempdir(), f"tune_smoke_{os.getpid()}.jsonl"
    )
    path_b = path_a + ".tuned.jsonl"
    profile_path = os.path.join(
        tempfile.gettempdir(), f"tune_smoke_profile_{os.getpid()}.json"
    )

    ids_untuned, waste_untuned, buckets_untuned = one_pass(path_a)
    profile = exec_tune.solve(
        load_events(path_a), max_shapes=len(DEFAULT_LENGTH_BUCKETS)
    )
    profile.save(profile_path)

    prev_env = os.environ.get(exec_config.PROFILE_ENV)
    os.environ[exec_config.PROFILE_ENV] = profile_path
    exec_config.reload_profile()
    try:
        ids_tuned, waste_tuned, buckets_tuned = one_pass(path_b)
    finally:
        if prev_env is None:
            os.environ.pop(exec_config.PROFILE_ENV, None)
        else:
            os.environ[exec_config.PROFILE_ENV] = prev_env
        exec_config.reload_profile()

    parity = float(np.mean(ids_untuned == ids_tuned))
    errors = []
    if waste_tuned >= waste_untuned:
        errors.append(
            f"padding_waste not reduced: {waste_untuned:.4f} -> "
            f"{waste_tuned:.4f}"
        )
    if parity != 1.0:
        errors.append(f"argmax parity {parity:.6f} != 1.0")
    if len(buckets_tuned) > len(DEFAULT_LENGTH_BUCKETS):
        errors.append(
            f"tuned lattice exceeds compile-shape budget: "
            f"{len(buckets_tuned)} > {len(DEFAULT_LENGTH_BUCKETS)}"
        )
    result = {
        "smoke_tune": True,
        "docs": len(eval_docs),
        "padding_waste": {
            "untuned": round(waste_untuned, 6),
            "tuned": round(waste_tuned, 6),
            "reduction": round(
                (waste_untuned - waste_tuned) / waste_untuned, 6
            ) if waste_untuned else 0.0,
        },
        "argmax_parity": parity,
        "lattice": {
            "untuned": list(buckets_untuned),
            "tuned": list(buckets_tuned),
        },
        "profile": {
            "version": profile.version,
            "path": profile_path,
            "tuned": {
                k: (list(v) if isinstance(v, tuple) else v)
                for k, v in profile.tuned.items()
            },
            "predicted_padded_reduction": profile.source[
                "predicted_padded_reduction"
            ],
        },
        "errors": errors[:5],
        "telemetry": {"untuned_jsonl": path_a, "tuned_jsonl": path_b},
    }
    result["ok"] = not errors
    return result


def smoke_wire(jsonl_path: str | None = None, *, trimmed: bool = False) -> dict:
    """CPU-safe device-encode wire smoke (docs/PERFORMANCE.md §11).

    All-unique short docs (20-50 bytes — BENCH_r05 config 1's wire-wall
    shape, where every doc pays host truncate/pack/pad freight and the
    in-flight dedup saves nothing) A/B'd host-pack vs device-encode:

      1. **parity** — the wire path (raw concatenated bytes + int32
         offsets, padded batch rebuilt inside the scoring jit) must be
         BIT-identical to host pack on gather and fused, on both the
         list[bytes] knob tier and the zero-copy DocBlock tier;
      2. **wire shrink** — ``score/wire_bytes`` per doc (buffer + index
         arrays, the exact whole-run counters) must drop >= 2x vs the
         padded host plane, and ``score/encoded_batches`` must tick (the
         tuner's liveness evidence);
      3. **speedup** — end-to-end all-unique throughput (DocBlock ingest
         included on the device arm) must improve >= 1.3x;
      4. **degraded ladder** — with a persistent ``score/pack`` fault the
         runner must fall to the host-pack rung and keep serving scores
         bit-identical to the fault-free host arm.

    ``trimmed=True`` is the tier-1-sized variant: smaller corpus and the
    wall-clock gate (speedup) is reported but not gated — tier-1 runs on
    noisy shared CPUs; the full run is the CI gate. The parity, wire-
    shrink, and degraded-ladder gates are deterministic and apply in both
    modes.
    """
    import gc
    import tempfile

    from spark_languagedetector_tpu import LanguageDetector, Table
    from spark_languagedetector_tpu.api.runner import BatchRunner
    from spark_languagedetector_tpu.ops.encode_device import DocBlock
    from spark_languagedetector_tpu.ops.encoding import texts_to_bytes
    from spark_languagedetector_tpu.resilience.faults import (
        FaultPlan,
        plan_scope,
    )
    from spark_languagedetector_tpu.resilience.policy import RetryPolicy
    from spark_languagedetector_tpu.telemetry import REGISTRY
    from spark_languagedetector_tpu.telemetry.export import JsonlSink

    REGISTRY.reset()
    path = jsonl_path or os.path.join(
        tempfile.gettempdir(), f"wire_smoke_{os.getpid()}.jsonl"
    )
    sink = JsonlSink(path)
    REGISTRY.add_sink(sink)
    errors: list[str] = []

    # Bigram exact vocab (config 1's shape): covered by BOTH gather (the
    # geometry-stable A/B reference) and the fused megakernel, so one
    # model serves every parity leg.
    langs = language_names(3)
    train_docs, train_labels = make_corpus(langs, 90, mean_len=200, seed=3)
    model = LanguageDetector(langs, [2], 2000).fit(
        Table({"lang": train_labels, "fulltext": train_docs})
    )
    weights, lut, cuckoo = model.profile.device_membership()

    # All-unique short docs: suffix-tagged so members are pairwise
    # distinct by construction (dedup saves nothing — every doc rides the
    # wire), 20-50 bytes so the padded host plane (128-byte floor bucket)
    # is mostly padding. That padding is exactly what the wire drops.
    n_docs = 800 if trimmed else 6000
    raw, _ = make_corpus(langs, n_docs, seed=11, len_range=(20, 50))
    docs = texts_to_bytes([f"{t} u{i}" for i, t in enumerate(raw)])
    block = DocBlock.from_bytes(docs)

    def build_runner(strategy: str, **kw) -> BatchRunner:
        kw.setdefault("ragged_transfer", False)
        return BatchRunner(
            weights=weights, lut=lut, cuckoo=cuckoo,
            spec=model.profile.spec, strategy=strategy, **kw,
        )

    def counters() -> dict:
        return dict(REGISTRY.snapshot()["counters"])

    def delta(after: dict, before: dict, key: str) -> int:
        return after.get(key, 0) - before.get(key, 0)

    # --- leg 1+2: parity + wire accounting, gather then fused --------------
    host = build_runner("gather", device_encode=False)
    c0 = counters()
    want = host.score(docs)
    c1 = counters()
    host_bpd = delta(c1, c0, "score/wire_bytes") / max(
        1, delta(c1, c0, "score/wire_docs")
    )

    dev = build_runner(
        "gather", device_encode=True,
        retry_policy=RetryPolicy(max_attempts=2, base_delay_s=0.0),
    )
    c2 = counters()
    got_knob = dev.score(docs)
    c3 = counters()
    dev_bpd = delta(c3, c2, "score/wire_bytes") / max(
        1, delta(c3, c2, "score/wire_docs")
    )
    encoded_batches = delta(c3, c2, "score/encoded_batches")
    # A DocBlock input engages the wire path structurally even with the
    # knob off — the host runner doubles as the zero-copy tier probe
    # (jit programs compile per runner instance; don't build spares).
    got_block = host.score(block)

    knob_bit_exact = bool(np.array_equal(got_knob, want))
    block_bit_exact = bool(np.array_equal(got_block, want))
    if not knob_bit_exact:
        errors.append("device-encode knob tier not bit-identical on gather")
    if not block_bit_exact:
        errors.append("DocBlock zero-copy tier not bit-identical on gather")
    if encoded_batches <= 0:
        errors.append("score/encoded_batches did not tick on the wire path")
    wire_reduction = host_bpd / dev_bpd if dev_bpd else 0.0
    if wire_reduction < 2.0:
        errors.append(
            f"wire bytes/doc reduction {wire_reduction:.2f}x < 2x "
            f"({host_bpd:.0f} -> {dev_bpd:.0f})"
        )

    fused_want = build_runner("fused", device_encode=False).score(docs)
    fused_got = build_runner("fused", device_encode=True).score(docs)
    fused_bit_exact = bool(np.array_equal(fused_got, fused_want))
    if not fused_bit_exact:
        errors.append("device-encode not bit-identical on fused")

    # --- leg 3: end-to-end all-unique A/B timing ---------------------------
    # The zero-copy claim is about INGEST: bytes arrive Arrow-backed (the
    # Spark/Parquet column shape) and the device arm views + joins them
    # without re-materializing Python bytes, while the host arm must
    # materialize list[bytes] before its per-doc truncate/pack loop.
    # Both arms start from the same Arrow array when pyarrow is present
    # (plain list[bytes] vs DocBlock.from_bytes otherwise); ingest is ON
    # both clocks. min-of-each-side is the robust estimator on shared
    # CPUs, with one retry round before declaring failure (see
    # smoke_cache's overhead gate for the bimodality rationale).
    try:
        import pyarrow as _pa

        _arr = _pa.array(docs, type=_pa.binary())

        def ingest_host():
            return _arr.to_pylist()

        def ingest_dev():
            return DocBlock.from_arrow(_arr)

        ingest = "arrow"
    except ImportError:

        def ingest_host():
            return docs

        def ingest_dev():
            return DocBlock.from_bytes(docs)

        ingest = "bytes"

    reps = 3 if trimmed else 9
    t_host: list[float] = []
    t_dev: list[float] = []
    dev.score(ingest_dev())  # warm the ingest form off the clock
    host.score(ingest_host())

    def ab_round(n_reps: int) -> None:
        gc.collect()
        gc.disable()
        try:
            for _ in range(n_reps):
                t0 = time.perf_counter()
                dev.score(ingest_dev())
                t_dev.append(time.perf_counter() - t0)
                t0 = time.perf_counter()
                host.score(ingest_host())
                t_host.append(time.perf_counter() - t0)
        finally:
            gc.enable()

    ab_round(reps)
    speedup = float(min(t_host) / min(t_dev))
    # Contention adds the same absolute overhead to both arms, which
    # compresses the ratio toward 1 — up to two retry rounds let the
    # min-estimator catch an uncontended window before declaring failure.
    for _ in range(2):
        if trimmed or speedup >= 1.3:
            break
        ab_round(reps)
        speedup = float(min(t_host) / min(t_dev))
    if not trimmed and speedup < 1.3:
        errors.append(f"all-unique e2e speedup {speedup:.2f}x < 1.3x")

    # --- leg 4: degraded ladder under a persistent pack fault --------------
    # Reuses the already-compiled device-encode runner (its retry policy
    # was built fast for exactly this leg).
    c4 = counters()
    with plan_scope(FaultPlan.parse("score/pack:error")):
        deg = dev.score(docs)
    c5 = counters()
    degraded_batches = delta(c5, c4, "resilience/degraded_batches")
    degraded_parity = float(np.mean(
        np.argmax(deg, axis=1) == np.argmax(want, axis=1)
    ))
    deg_bit_exact = bool(np.array_equal(deg, want))
    if degraded_batches <= 0:
        errors.append("persistent score/pack fault did not degrade")
    if not deg_bit_exact:
        errors.append("degraded host-pack rung not bit-identical")
    if degraded_parity != 1.0:
        errors.append(f"degraded parity {degraded_parity:.6f} != 1.0")

    REGISTRY.flush()
    REGISTRY.remove_sink(sink)
    result = {
        "smoke_wire": True,
        "trimmed": trimmed,
        "docs": len(docs),
        "parity": {
            "knob_bit_exact": knob_bit_exact,
            "block_bit_exact": block_bit_exact,
            "fused_bit_exact": fused_bit_exact,
            "degraded_bit_exact": deg_bit_exact,
            "degraded_argmax": degraded_parity,
        },
        "wire": {
            "host_bytes_per_doc": round(host_bpd, 2),
            "device_bytes_per_doc": round(dev_bpd, 2),
            "reduction": round(wire_reduction, 4),
            "encoded_batches": encoded_batches,
        },
        "speedup_all_unique": round(speedup, 4),
        "ingest": ingest,
        "degraded_batches": degraded_batches,
        "errors": errors[:5],
        "telemetry": {"jsonl": path},
    }
    result["ok"] = not errors
    return result


def smoke_cache(jsonl_path: str | None = None, *, trimmed: bool = False) -> dict:
    """CPU-safe redundancy-eliminator smoke (docs/PERFORMANCE.md §10).

    Drives a Zipf-duplicated corpus (~70% duplicate mass — the serve
    traffic shape) through all three front ends with the two-level
    eliminator on, and A/B's it against the dedup/cache-off baseline:

      1. **batch** — the runner's in-flight dedup, interleaved on/off
         timing passes; scores must stay bit-identical (gather strategy)
         and the duplicated corpus must run ≥ 1.5× faster end-to-end;
      2. **all-unique overhead** — the same A/B on a duplicate-free
         corpus; the dict build + scatter must cost ≤ 3% end-to-end;
      3. **stream** — ``run_stream`` over duplicated micro-batches with a
         checkpoint, parity vs the dedup-off transform;
      4. **fleet** — a 2-replica fleet behind the router front with
         concurrent clients replaying duplicated texts through the
         version-keyed serve cache, a fleet-wide two-phase hot-swap
         mid-run; per-version score parity must be exactly 1.0 (a stale
         cache answer — any pre-swap bits served post-swap — is a parity
         mismatch by construction, because the two model versions are
         fitted on different corpora), and the cache must demonstrably
         hit.

    ``trimmed=True`` is the tier-1-sized variant: smaller legs, and the
    two wall-clock gates (speedup, overhead) are reported but not gated —
    tier-1 runs on noisy shared CPUs where a 3% timing bound would flake;
    the full run is the CI gate.
    """
    import tempfile
    import threading

    from spark_languagedetector_tpu import LanguageDetector, Table
    from spark_languagedetector_tpu.ops.encoding import texts_to_bytes
    from spark_languagedetector_tpu.serve.client import ServeClient, ServeHTTPError
    from spark_languagedetector_tpu.serve.fleet import ServeFleet
    from spark_languagedetector_tpu.serve.router import RouterServer
    from spark_languagedetector_tpu.stream.microbatch import memory_source, run_stream
    from spark_languagedetector_tpu.telemetry import REGISTRY
    from spark_languagedetector_tpu.telemetry.export import JsonlSink

    REGISTRY.reset()
    path = jsonl_path or os.path.join(
        tempfile.gettempdir(), f"cache_smoke_{os.getpid()}.jsonl"
    )
    sink = JsonlSink(path)
    REGISTRY.add_sink(sink)
    errors: list[str] = []

    # gram_lengths [1,2,3] keep every runner on the gather strategy: the
    # geometry-stable A/B reference, so dedup scatter-back and cached
    # results are bit-identical to the baseline (docs/SERVING.md §1).
    langs = language_names(3)
    docs, labels = make_corpus(langs, 60, mean_len=200, seed=3)
    model_a = LanguageDetector(langs, [1, 2, 3], 200).fit(
        Table({"lang": labels, "fulltext": docs})
    )
    docs_b, labels_b = make_corpus(langs, 60, mean_len=200, seed=9)
    model_b = LanguageDetector(langs, [1, 2, 3], 150).fit(
        Table({"lang": labels_b, "fulltext": docs_b})
    )
    runner = model_a._get_runner()

    # Zipf-duplicated workload at ~70% duplicate mass (the acceptance
    # shape): every pool document appears at least once (so distinct/total
    # is exactly the pool fraction) and the remaining 70% of the corpus is
    # drawn from the pool under a Zipf law — the heavy-tailed repetition
    # real serve traffic shows (trending content, retries, short texts).
    n_zipf = 300 if trimmed else 1200
    n_pool = max(2, int(n_zipf * 0.3))
    pool_raw, _ = make_corpus(langs, n_pool, mean_len=200, seed=21)
    # Suffix-tag the pool so its members are pairwise distinct by
    # construction (the tiny word lists can collide on short docs).
    pool = [f"{t} p{i}" for i, t in enumerate(pool_raw)]
    rng = np.random.default_rng(35)
    zipf_p = _zipf_probs(n_pool, s=1.2)
    zipf_texts = pool + [
        pool[i] for i in rng.choice(n_pool, n_zipf - n_pool, p=zipf_p)
    ]
    zipf_texts = [zipf_texts[i] for i in rng.permutation(n_zipf)]
    zipf_docs = texts_to_bytes(zipf_texts)
    dup_mass = 1.0 - len(set(zipf_docs)) / len(zipf_docs)
    # The overhead leg uses a larger corpus than the speedup leg: the 3%
    # bound is tighter than one pass's scheduler jitter at small sizes,
    # and the jitter is absolute (~fractions of a ms), so longer passes
    # shrink it relative to the signal. Trimmed mode skips the leg
    # entirely — neither wall-clock gate applies there, and the extra
    # corpus' compile shapes would be pure tier-1 time.
    uniq_docs = None
    if not trimmed:
        uniq_raw, _ = make_corpus(langs, 8 * n_zipf, mean_len=200, seed=43)
        uniq_texts = [f"{t} u{i}" for i, t in enumerate(uniq_raw)]
        uniq_docs = texts_to_bytes(uniq_texts)

    def timed_pass(batch_docs, dedup_on: bool) -> tuple[float, np.ndarray]:
        runner.dedup = dedup_on
        t0 = time.perf_counter()
        out = runner.score(batch_docs)
        return time.perf_counter() - t0, out

    # --- leg 1+2: batch A/B, interleaved passes, medians -------------------
    import gc

    reps = 3 if trimmed else 9
    t_dup = {True: [], False: []}
    t_uni = {True: [], False: []}
    scores_on = scores_off = None
    uni_on = uni_off = None
    timed_pass(zipf_docs, True)  # warm the compile shapes off the clock
    if uniq_docs is not None:
        timed_pass(uniq_docs, True)

    def ab_round(batch_docs, n_reps, on_times, off_times):
        out_on = out_off = None
        gc.collect()
        # A collection (or any host hiccup) landing inside one pass skews
        # it; the estimator below tolerates that, but don't invite it.
        gc.disable()
        try:
            for _ in range(n_reps):
                dt, out_on = timed_pass(batch_docs, True)
                on_times.append(dt)
                dt, out_off = timed_pass(batch_docs, False)
                off_times.append(dt)
        finally:
            gc.enable()
        return out_on, out_off

    scores_on, scores_off = ab_round(
        zipf_docs, reps, t_dup[True], t_dup[False]
    )
    if uniq_docs is not None:
        uni_on, uni_off = ab_round(uniq_docs, reps, t_uni[True], t_uni[False])
        # Shared-CPU pass times here are bimodal — an uncontended fast
        # mode and a ~2x contended mode that persists across several
        # passes — so paired ratios can land 2x off in either direction.
        # min-of-each-side is the robust estimator: both sides hit the
        # uncontended mode within a few reps, and a REAL dedup overhead
        # shifts every on-pass, the minimum included. One retry round
        # before declaring failure keeps a wholly-contended first round
        # from flaking the gate; a genuine regression fails both.
        overhead = float(min(t_uni[True]) / min(t_uni[False]) - 1.0)
        if overhead > 0.03:
            ab_round(uniq_docs, reps, t_uni[True], t_uni[False])
            overhead = float(min(t_uni[True]) / min(t_uni[False]) - 1.0)
    else:
        overhead = None
    runner.dedup = True
    speedup = float(min(t_dup[False]) / min(t_dup[True]))
    batch_bit_exact = bool(np.array_equal(scores_on, scores_off))
    batch_parity = float(np.mean(
        np.argmax(scores_on, axis=1) == np.argmax(scores_off, axis=1)
    ))
    if not batch_bit_exact:
        errors.append("batch dedup scores not bit-identical on gather")
    if batch_parity != 1.0:
        errors.append(f"batch argmax parity {batch_parity:.6f} != 1.0")
    if uniq_docs is not None and not np.array_equal(uni_on, uni_off):
        errors.append("all-unique dedup pass changed scores")
    if not trimmed and speedup < 1.5:
        errors.append(
            f"duplicated-corpus speedup {speedup:.2f}x < 1.5x"
        )
    if not trimmed and overhead > 0.03:
        errors.append(f"all-unique overhead {overhead:.1%} > 3%")

    # --- leg 3: stream with dedup + checkpoint -----------------------------
    ck_path = os.path.join(
        tempfile.gettempdir(), f"cache_smoke_ck_{os.getpid()}.json"
    )
    if os.path.exists(ck_path):
        os.remove(ck_path)
    stream_rows = [{"fulltext": t} for t in zipf_texts]
    batch_rows = 64
    got_tables: list = []
    query = run_stream(
        model_a, memory_source(stream_rows, batch_rows), got_tables.append,
        checkpoint_path=ck_path,
    )
    stream_pred = [
        v for tbl in got_tables for v in tbl.column("lang").tolist()
    ]
    runner.dedup = False
    want_tbl = model_a.transform(Table({"fulltext": zipf_texts}))
    runner.dedup = True
    stream_want = want_tbl.column("lang").tolist()
    stream_parity = float(np.mean(
        np.asarray(stream_pred) == np.asarray(stream_want)
    )) if stream_pred else 0.0
    if stream_parity != 1.0:
        errors.append(f"stream dedup parity {stream_parity:.6f} != 1.0")
    if query.batches != -(-len(stream_rows) // batch_rows):
        errors.append("stream did not sink every batch")

    # --- leg 4: 2-replica fleet + cache + mid-run hot-swap -----------------
    # In-memory models (ServeFleet's shared-object form): both runners are
    # already compiled by the legs above, so the fleet leg measures cache/
    # swap semantics, not 10+ seconds of fresh-instance jit compiles. The
    # disk-load + /admin/swap HTTP path is smoke_fleet's gate.
    runner_a = model_a._get_runner()
    runner_b = model_b._get_runner()
    runner_b.score(zipf_docs[:8])  # warm b's compile off the fleet clock

    n_clients = 2 if trimmed else 4
    rounds = 6 if trimmed else 12
    docs_per_req = 4
    swap_round = rounds // 2
    v_old, v_new = "v1", [None]
    barrier = threading.Barrier(n_clients)
    lock = threading.Lock()
    responses: list[tuple[list, np.ndarray, str]] = []

    fleet = ServeFleet(
        [model_a] * 2,
        router_kw=dict(probe_interval_ms=40.0, probe_timeout_s=2.0),
        max_wait_ms=4, max_rows=64, max_queue_rows=512,
    ).start()
    front = RouterServer(fleet.router, fleet=fleet, port=0).start()
    host, port = front.address
    try:
        def drive(ci: int) -> None:
            crng = np.random.default_rng(500 + ci)
            client = ServeClient(host, port)
            for r in range(rounds):
                try:
                    barrier.wait(timeout=60)
                except threading.BrokenBarrierError:
                    pass
                if ci == 0 and r == swap_round:
                    # The fleet's coordinated two-phase flip (prepare
                    # everywhere, then drain+commit behind the version
                    # pin) — same protocol /admin/swap drives.
                    v_new[0] = fleet.swap(models=[model_b] * 2)
                    continue
                # All clients draw from the SAME duplicated pool — the
                # cross-request hits are the point of the serve cache.
                picks = crng.choice(len(zipf_texts), docs_per_req)
                texts = [zipf_texts[int(i)] for i in picks]
                try:
                    scores, meta = client.score(texts)
                except (ServeHTTPError, OSError) as e:
                    with lock:
                        errors.append(f"fleet client {ci} round {r}: {e}")
                    continue
                with lock:
                    responses.append((texts, scores, meta["version"]))

        threads = [
            threading.Thread(target=drive, args=(ci,))
            for ci in range(n_clients)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
    finally:
        front.stop()
        fleet.close()

    # Per-version bit parity = the zero-staleness gate: the versions are
    # fitted on different corpora, so a cached pre-swap row served for a
    # post-swap request cannot bit-match the post-swap runner.
    stale = checked = 0
    versions_served: set[str] = set()
    for texts, scores, version in responses:
        versions_served.add(version)
        direct = (runner_a if version == v_old else runner_b).score(
            texts_to_bytes(texts)
        )
        checked += 1
        if scores.shape != direct.shape or not np.array_equal(scores, direct):
            stale += 1
    fleet_parity = 1.0 if checked and stale == 0 else (
        round(1.0 - stale / checked, 6) if checked else 0.0
    )
    if fleet_parity != 1.0:
        errors.append(
            f"fleet per-version parity {fleet_parity} != 1.0 "
            f"({stale} stale/mismatched responses)"
        )
    if v_new[0] is None or versions_served != {v_old, v_new[0]}:
        errors.append(f"swap not observed (served {sorted(versions_served)})")

    snap = REGISTRY.snapshot()
    counters = snap["counters"]
    hits = int(counters.get("cache/hits", 0))
    lookups = int(counters.get("cache/lookups", 0))
    hit_rate = hits / lookups if lookups else 0.0
    rows_in = int(counters.get("dedup/rows_in", 0))
    rows_unique = int(counters.get("dedup/rows_unique", 0))
    wire_saved = int(counters.get("dedup/bytes_saved", 0)) + int(
        counters.get("cache/bytes_saved", 0)
    )
    if hits <= 0:
        errors.append("serve cache never hit under duplicated traffic")
    if rows_unique >= rows_in or rows_in <= 0:
        errors.append("in-flight dedup eliminated nothing")

    result = {
        "smoke_cache": True,
        "trimmed": trimmed,
        "duplicate_mass": round(dup_mass, 4),
        "batch": {
            "docs": n_zipf,
            "speedup_duplicated": round(speedup, 3),
            "overhead_all_unique": (
                None if overhead is None else round(overhead, 4)
            ),
            "bit_exact": batch_bit_exact,
            "argmax_parity": batch_parity,
            "docs_per_s_on": round(n_zipf / float(np.min(t_dup[True])), 1),
            "docs_per_s_off": round(n_zipf / float(np.min(t_dup[False])), 1),
        },
        "stream": {
            "batches": query.batches,
            "parity": stream_parity,
        },
        "fleet": {
            "replicas": 2,
            "answered": len(responses),
            "per_version_parity": fleet_parity,
            "stale_answers": stale,
            "versions_served": sorted(versions_served),
            "swap_to": v_new[0],
        },
        "cache": {
            "hits": hits,
            "lookups": lookups,
            "hit_rate": round(hit_rate, 4),
            "evictions": int(counters.get("cache/evictions", 0)),
        },
        "dedup": {
            "rows_in": rows_in,
            "rows_unique": rows_unique,
            "unique_ratio": round(rows_unique / rows_in, 4) if rows_in else 1.0,
        },
        "wire_bytes_saved": wire_saved,
        "errors": errors[:8],
        "telemetry": telemetry_block(path),
    }
    result["ok"] = not errors
    REGISTRY.remove_sink(sink)
    return result


def smoke_segment(jsonl_path: str | None = None, *, trimmed: bool = False) -> dict:
    """CPU-safe segmentation smoke (docs/SEGMENTATION.md): the span-level
    code-switch result type through every front end, hard-gated.

    Drives a block-structured synthetic code-switch corpus with KNOWN
    span boundaries (:func:`make_codeswitch_corpus`) through:

      1. **batch** — ``resultMode="segment"`` transform; byte-span macro
         F1 against the ground truth must be ≥ 0.85, and top-3 must
         contain the dominant language of word-level mixed docs ≥ 0.98;
      2. **calibration** — temperatures fit on a held-out split, ECE
         measured on a DISJOINT eval split: ≤ 0.10 after the fit and
         strictly better than uncalibrated (T = 1);
      3. **stream** — ``run_stream`` over the same corpus in segment
         mode; the JSON result column must equal the batch transform's
         exactly (string equality — the decode is deterministic and the
         JSON canonical);
      4. **fleet** — 2 replicas behind the router front sharing ONE
         score cache, concurrent clients mixing segment requests (model
         defaults AND per-request knob overrides) with label-mode
         ``/detect`` traffic, and a mid-run two-phase hot-swap to a
         model fitted on a different corpus and calibrated differently:
         every response must equal the direct decode/predict of exactly
         the version that served it — one stale or cross-mode (or
         cross-knob) cache answer is a mismatch by construction;
      5. **whole-doc pin** — the runner's ``score`` bytes after all the
         segment traffic must be bit-identical to the bytes captured
         before any of it (gather strategy): the new output mode must
         not perturb the existing one.

    ``trimmed=True`` is the tier-1-sized variant (fewer docs/clients —
    all five gates still hard); the full run is the CI gate.
    """
    import tempfile
    import threading

    from spark_languagedetector_tpu import LanguageDetector, Table
    from spark_languagedetector_tpu.ops.encoding import texts_to_bytes
    from spark_languagedetector_tpu.segment import (
        SegmentOptions,
        segment_documents,
    )
    from spark_languagedetector_tpu.segment.calibrate import (
        calibrated_probs,
        expected_calibration_error,
        normalize_scores,
    )
    from spark_languagedetector_tpu.serve.cache import ScoreCache
    from spark_languagedetector_tpu.serve.client import ServeClient, ServeHTTPError
    from spark_languagedetector_tpu.serve.fleet import ServeFleet
    from spark_languagedetector_tpu.serve.router import RouterServer
    from spark_languagedetector_tpu.stream.microbatch import memory_source, run_stream
    from spark_languagedetector_tpu.telemetry import REGISTRY
    from spark_languagedetector_tpu.telemetry.export import JsonlSink

    REGISTRY.reset()
    path = jsonl_path or os.path.join(
        tempfile.gettempdir(), f"segment_smoke_{os.getpid()}.jsonl"
    )
    sink = JsonlSink(path)
    REGISTRY.add_sink(sink)
    errors: list[str] = []

    # gram_lengths [1,2,3] keep the runners on the gather strategy — the
    # geometry-stable reference whose whole-doc bytes the pin gate
    # compares bit-for-bit.
    langs = ["en", "de", "fr"]
    docs_a, labels_a = make_corpus(langs, 60, mean_len=300, seed=3)
    model_a = LanguageDetector(langs, [1, 2, 3], 200).fit(
        Table({"lang": labels_a, "fulltext": docs_a})
    )
    docs_b, labels_b = make_corpus(langs, 60, mean_len=300, seed=9)
    model_b = LanguageDetector(langs, [1, 2, 3], 150).fit(
        Table({"lang": labels_b, "fulltext": docs_b})
    )
    runner_a = model_a._get_runner()

    # Whole-doc pin capture: BEFORE any segment-mode work touches the
    # process.
    pin_docs = texts_to_bytes(docs_a[:24] + ["", "köln 京都 short"])
    scores_pre = runner_a.score(pin_docs)

    # --- leg 2: calibration (fit split vs disjoint eval split) -------------
    n_heldout = 60 if trimmed else 150
    hd, hl = make_corpus(langs, 2 * n_heldout, mean_len=250, seed=77)
    fit_docs, fit_labels = hd[:n_heldout], hl[:n_heldout]
    eval_docs, eval_labels = hd[n_heldout:], hl[n_heldout:]
    model_a.calibrate(Table({"fulltext": fit_docs, "lang": fit_labels}))
    model_b.calibrate(Table({"fulltext": hd, "lang": hl}))  # different temps
    eval_bytes = texts_to_bytes(eval_docs)
    norm = normalize_scores(
        np.asarray(runner_a.score(eval_bytes), dtype=np.float64),
        [len(d) for d in eval_bytes],
    )
    y = np.asarray([langs.index(l) for l in eval_labels])
    ece_uncal = expected_calibration_error(
        calibrated_probs(norm, np.ones(len(langs))), y
    )
    ece_cal = expected_calibration_error(
        calibrated_probs(norm, model_a.calibration.temperatures), y
    )
    if ece_cal > 0.10:
        errors.append(f"calibrated ECE {ece_cal:.4f} > 0.10")
    if not ece_cal < ece_uncal:
        errors.append(
            f"calibration not strictly better: {ece_cal:.4f} vs "
            f"uncalibrated {ece_uncal:.4f}"
        )

    # --- leg 1: batch span F1 + top-k ---------------------------------------
    n_seg = 20 if trimmed else 80
    seg_docs, seg_truth = make_codeswitch_corpus(langs, n_seg, seed=23)
    model_seg = model_a.copy().set_result_mode("segment")
    model_seg.calibration = model_a.calibration
    batch_out = model_seg.transform(Table({"fulltext": seg_docs}))
    batch_json = batch_out.column(model_seg.get_output_col()).tolist()
    batch_results = [json.loads(s) for s in batch_json]
    seg_bytes = texts_to_bytes(seg_docs)
    f1 = macro_span_f1(
        span_byte_f1(tr, r["spans"], len(d))
        for tr, r, d in zip(seg_truth, batch_results, seg_bytes)
    )
    if f1 < 0.85:
        errors.append(f"segmentation span F1 {f1:.4f} < 0.85")

    n_mixed = 40 if trimmed else 200
    mixed = make_mixed_corpus("en", "de", n_mixed, mean_len=400,
                              frac_a=0.7, seed=11)
    mixed_res = model_seg.segment(mixed)
    topk_hit = float(np.mean([
        "en" in {e["lang"] for e in r["topk"]} for r in mixed_res
    ]))
    if topk_hit < 0.98:
        errors.append(f"top-3 true-label hit {topk_hit:.4f} < 0.98 on "
                      "mixed docs")

    # --- leg 3: stream parity ----------------------------------------------
    stream_rows = [{"fulltext": t} for t in seg_docs]
    got_tables: list = []
    query = run_stream(
        model_seg, memory_source(stream_rows, 8), got_tables.append
    )
    stream_json = [
        v for tbl in got_tables
        for v in tbl.column(model_seg.get_output_col()).tolist()
    ]
    if stream_json != batch_json:
        errors.append("stream segment results differ from batch transform")
    if query.batches != -(-len(stream_rows) // 8):
        errors.append("stream did not sink every batch")

    # --- leg 4: fleet + shared cache + mid-run hot-swap ---------------------
    model_b_seg = model_b.copy().set_result_mode("segment")
    model_b_seg.calibration = model_b.calibration
    model_b_seg._get_runner().score(seg_bytes[:2])  # warm off the clock
    shared_cache = ScoreCache()
    opts_default = SegmentOptions()
    opts_k1 = SegmentOptions(top_k=1)

    n_clients = 2 if trimmed else 4
    rounds = 6 if trimmed else 12
    swap_round = rounds // 2
    v_old, v_new = "v1", [None]
    barrier = threading.Barrier(n_clients)
    lock = threading.Lock()
    responses: list[tuple] = []

    fleet = ServeFleet(
        [model_seg] * 2,
        router_kw=dict(probe_interval_ms=40.0, probe_timeout_s=2.0),
        max_wait_ms=4, max_rows=64, max_queue_rows=512,
        cache=shared_cache,
    ).start()
    front = RouterServer(fleet.router, fleet=fleet, port=0).start()
    host, port = front.address
    try:
        def drive(ci: int) -> None:
            crng = np.random.default_rng(700 + ci)
            client = ServeClient(host, port)
            for r in range(rounds):
                try:
                    barrier.wait(timeout=60)
                except threading.BrokenBarrierError:
                    pass
                if ci == 0 and r == swap_round:
                    v_new[0] = fleet.swap(models=[model_b_seg] * 2)
                    continue
                picks = crng.choice(len(seg_docs), 3)
                texts = [seg_docs[int(i)] for i in picks]
                kind = ("segment", "segment_k1", "label")[r % 3]
                try:
                    if kind == "segment":
                        out, meta = client.segment(texts)
                    elif kind == "segment_k1":
                        out, meta = client.segment(texts, top_k=1)
                    else:
                        out, meta = client.detect(texts)
                except (ServeHTTPError, OSError) as e:
                    with lock:
                        errors.append(f"fleet client {ci} round {r}: {e}")
                    continue
                with lock:
                    responses.append((kind, texts, out, meta))

        threads = [
            threading.Thread(target=drive, args=(ci,))
            for ci in range(n_clients)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
    finally:
        front.stop()
        fleet.close()

    # Zero-staleness / zero-cross-mode gate: every response must equal
    # the direct decode of exactly the version that served it. The two
    # versions are fitted on different corpora AND calibrated on
    # different held-out sets, so a cached pre-swap entry served
    # post-swap (or a k=1 entry served for a k=3 request, or a label id
    # for a segment request) cannot match.
    def direct(version, kind, texts):
        model = model_seg if version == v_old else model_b_seg
        byte_docs = texts_to_bytes(texts)
        if kind == "label":
            # Segment-mode models answer /detect in their segment
            # default (docs/SERVING.md §11).
            return segment_documents(
                model._get_runner(), byte_docs, langs,
                options=opts_default, calibration=model.calibration,
            )
        return segment_documents(
            model._get_runner(), byte_docs, langs,
            options=opts_k1 if kind == "segment_k1" else opts_default,
            calibration=model.calibration,
        )

    stale = 0
    versions_served: set[str] = set()
    kinds_served: set[str] = set()
    for kind, texts, out, meta in responses:
        versions_served.add(meta["version"])
        kinds_served.add(kind)
        if out != direct(meta["version"], kind, texts):
            stale += 1
    if stale:
        errors.append(
            f"{stale}/{len(responses)} stale or cross-mode fleet answers"
        )
    if v_new[0] is None or versions_served != {v_old, v_new[0]}:
        errors.append(f"swap not observed (served {sorted(versions_served)})")
    if kinds_served != {"segment", "segment_k1", "label"}:
        errors.append(f"request mix incomplete (served {sorted(kinds_served)})")

    # --- leg 5: whole-doc pin ----------------------------------------------
    scores_post = runner_a.score(pin_docs)
    whole_doc_bit_identical = bool(np.array_equal(scores_pre, scores_post))
    if not whole_doc_bit_identical:
        errors.append(
            "whole-doc scores changed after segment traffic (gather)"
        )

    snap = REGISTRY.snapshot()
    counters = snap["counters"]
    seg_docs_n = int(counters.get("segment/docs", 0))
    result = {
        "smoke_segment": True,
        "trimmed": trimmed,
        "span_f1": round(f1, 4),
        "topk_hit": round(topk_hit, 4),
        "calibration": {
            "ece_uncalibrated": round(ece_uncal, 4),
            "ece_calibrated": round(ece_cal, 4),
            "fit_meta": dict(model_a.calibration.meta),
        },
        "stream": {
            "batches": query.batches,
            "parity": 1.0 if stream_json == batch_json else 0.0,
        },
        "fleet": {
            "replicas": 2,
            "answered": len(responses),
            "stale_or_cross_mode": stale,
            "versions_served": sorted(versions_served),
            "swap_to": v_new[0],
            "cache_hits": int(counters.get("cache/hits", 0)),
        },
        "segment_counters": {
            "docs": seg_docs_n,
            "rejects": int(counters.get("segment/rejects", 0)),
            "spans": int(counters.get("segment/spans", 0)),
        },
        "whole_doc_bit_identical": whole_doc_bit_identical,
        "errors": errors[:8],
        "telemetry": telemetry_block(path),
    }
    result["ok"] = not errors
    REGISTRY.remove_sink(sink)
    return result


def fit_scaling_probe(n_devices: int) -> dict:
    """Child half of the fit-scaling leg: run in a subprocess whose
    XLA_FLAGS forced ``n_devices`` virtual CPU devices. Fits the probe
    corpus through the public estimator (device backend — >1 device
    resolves the fit mesh, so 8 devices exercise the table-sharded
    accumulator + collective top-k merge), reports warm docs/s, the
    fit-stage breakdown including ``fit/finalize``/``fit/collect``, and
    the collect-bytes contract numbers."""
    import jax

    # The axon sitecustomize force-sets jax_platforms programmatically; the
    # programmatic update (not the env var) is what actually wins — same
    # dance as tests/conftest.py.
    jax.config.update("jax_platforms", "cpu")

    from spark_languagedetector_tpu import LanguageDetector, Table
    from spark_languagedetector_tpu.telemetry import REGISTRY

    devices = jax.devices()
    if len(devices) < n_devices:
        return {"error": f"wanted {n_devices} devices, have {len(devices)}"}
    langs = language_names(6)
    docs, labels = make_corpus(langs, 240, mean_len=300, seed=7)
    table = Table({"lang": labels, "fulltext": docs})

    def det(backend):
        return (
            LanguageDetector(langs, [1, 2, 3], 400)
            .set_vocab_mode("hashed")
            .set_hash_bits(16)
            .set_fit_backend(backend)
        )

    host_model = det("cpu").fit(table)
    dev_model = det("device").fit(table)  # cold (compiles)
    stages_before = REGISTRY.stage_summary()
    collect_before = REGISTRY.snapshot()["counters"].get(
        "fit/collect_bytes", 0
    )
    t0 = time.perf_counter()
    dev_model = det("device").fit(table)
    t_warm = time.perf_counter() - t0
    stages = _fit_stage_delta(stages_before, REGISTRY.stage_summary())
    collect_bytes = (
        REGISTRY.snapshot()["counters"].get("fit/collect_bytes", 0)
        - collect_before
    )
    spec = det("device")._vocab_spec()
    table_bytes = spec.id_space_size * len(langs) * 4
    parity = np.array_equal(
        dev_model.profile.ids, host_model.profile.ids
    ) and np.array_equal(dev_model.profile.weights, host_model.profile.weights)
    return {
        "devices": n_devices,
        "fit_docs_per_s": round(len(docs) / t_warm, 1),
        "fit_train_docs": len(docs),
        "fit_stages": stages,
        "fit_collect_bytes": int(collect_bytes),
        # What the pre-device-finalize fit moved per finalize: the whole
        # [V, L] table — the "before" of the before/after collect ratio.
        "full_table_bytes": int(table_bytes),
        "collect_ratio": round(collect_bytes / table_bytes, 6),
        "parity_vs_host": bool(parity),
    }


def smoke_zoo(jsonl_path: str | None = None, *, trimmed: bool = False) -> dict:
    """CPU-safe multi-tenant model-zoo smoke (docs/SERVING.md §12a).

    Spins up ~32 tenants (distinct seeded models over ONE shared spec, so
    the whole population costs one compile-cache entry) behind a single
    zoo-backed HTTP server whose residency budget holds only a quarter of
    them — every round of the concurrent per-tenant socket clients forces
    LRU evictions and cold reloads mid-traffic. The script then (1) fires
    a noisy-neighbor burst at a small-quota tenant and (2) runs one
    tenant-scoped refit hot-swap mid-traffic.

    Hard gates (``main()`` exits nonzero): per-tenant argmax parity
    exactly 1.0 against each tenant's own direct runner for the version
    that answered; zero cross-tenant answers (a pairwise-distinct
    signature precheck makes parity discriminating, and
    ``zoo/cross_tenant_rejects`` must stay 0); ≥ 1 residency eviction AND
    ≥ 1 cold *reload* (a tenant paged out and back) with leased versions
    never evicted (structural — the LRU skips busy tenants); the noisy
    burst sheds only the noisy tenant (every victim tenant's queue-local
    shed tally stays 0); and the refit moves exactly one tenant's
    version. ``trimmed=True`` is the tier-1-sized variant (fewer
    tenants/clients, same gates).
    """
    import itertools
    import tempfile
    import threading

    from spark_languagedetector_tpu import (
        LanguageDetector,
        LanguageDetectorModel,
        Table,
    )
    from spark_languagedetector_tpu.ops.encoding import texts_to_bytes
    from spark_languagedetector_tpu.serve.client import ServeClient, ServeHTTPError
    from spark_languagedetector_tpu.serve.server import ServingServer
    from spark_languagedetector_tpu.telemetry import REGISTRY
    from spark_languagedetector_tpu.telemetry.export import JsonlSink
    from spark_languagedetector_tpu.zoo import ModelZoo, TenantQuota

    REGISTRY.reset()
    path = jsonl_path or os.path.join(
        tempfile.gettempdir(), f"zoo_smoke_{os.getpid()}.jsonl"
    )
    sink = JsonlSink(path)
    REGISTRY.add_sink(sink)
    server = None
    try:

        n_tenants = 8 if trimmed else 32
        n_clients = 4 if trimmed else 6
        rounds = 10 if trimmed else 16
        docs_per_req = 4
        resident_cap = max(3, n_tenants // 4)
        langs = ["l0", "l1", "l2"]
        alphabet = "abcdxyz"

        # Eval corpus: fixed random short docs over the shared alphabet.
        rng = np.random.default_rng(140)
        letters = np.array(list(alphabet))
        eval_texts = [
            "".join(rng.choice(letters, size=int(rng.integers(12, 32))))
            for _ in range(24)
        ]
        eval_docs = texts_to_bytes(eval_texts)

        def tenant_model(seed: int) -> LanguageDetectorModel:
            # 1-gram tables: a 256-row dense table keeps every cold reload's
            # runner build O(ms) (the 2-gram 65k-row dense form hits XLA's
            # slow constant-folding path per rebuilt program), while seeded
            # random per-byte weights keep tenant signatures distinct.
            trng = np.random.default_rng(seed)
            gram_map = {
                a.encode(): trng.random(len(langs)).tolist() for a in alphabet
            }
            return LanguageDetectorModel.from_gram_map(gram_map, [1], langs)

        # Per-tenant models with a pairwise-distinct label signature over the
        # eval corpus — what makes "parity vs your OWN runner" a
        # discriminating zero-cross-tenant-answers check. Seeds retry
        # deterministically on a (vanishingly unlikely) signature collision.
        tenants = [f"t{i:02d}" for i in range(n_tenants)]
        models: dict = {}
        signatures: set = set()
        expected: dict[tuple[str, str], list[str]] = {}
        for i, name in enumerate(tenants):
            for bump in range(0, 5000, 1000):
                model = tenant_model(200 + i + bump)
                ids = model._get_runner().predict_ids(eval_docs)
                sig = tuple(int(x) for x in ids)
                if sig not in signatures:
                    signatures.add(sig)
                    models[name] = model
                    expected[(name, "v1")] = [langs[x] for x in sig]
                    break
            else:
                raise RuntimeError(f"no distinct signature for {name}")
        distinct_ok = len(signatures) == n_tenants

        zoo = ModelZoo(
            resident_models=resident_cap,
            max_wait_ms=4, max_rows=64, max_queue_rows=512,
        )
        for name in tenants:
            zoo.add_tenant(name, models[name])
        # The burst target: a deliberately tiny quota lane, outside the
        # regular rotation so victim tallies are unambiguous.
        zoo.add_tenant(
            "noisy", tenant_model(990), quota=TenantQuota(max_queue_rows=8)
        )
        server = ServingServer(zoo, port=0).start()
        host, port = server.address

        refit_tenant = tenants[0]
        refit_version: list[str | None] = [None]
        noisy_results = {"expected_sheds": 0, "answered": 0}
        burst_round = rounds // 3
        refit_round = rounds - 3

        barrier = threading.Barrier(n_clients)
        lock = threading.Lock()
        responses: list[tuple[str, str, int, list]] = []  # tenant, ver, lo, labels
        errors: list[str] = []

        def drive(ci: int) -> None:
            crng = np.random.default_rng(400 + ci)
            client = ServeClient(host, port)

            def one_request(tenant: str, tag: str) -> None:
                lo = int(crng.integers(0, len(eval_texts) - docs_per_req))
                texts = eval_texts[lo:lo + docs_per_req]
                try:
                    got, meta = client.detect(texts, tenant=tenant)
                except (ServeHTTPError, OSError) as e:
                    with lock:
                        errors.append(f"client {ci} {tag} [{tenant}]: {e}")
                    return
                with lock:
                    responses.append((tenant, meta["version"], lo, got))

            for r in range(rounds):
                try:
                    barrier.wait(timeout=120)
                except threading.BrokenBarrierError:
                    pass
                if ci == 0 and r == burst_round:
                    # Noisy-neighbor burst: each oversized bulk request blows
                    # the tenant's 8-row quota lane and must shed (503) —
                    # while every other client is mid-round on its own lane.
                    for k in range(5):
                        try:
                            client.detect(
                                eval_texts[: 3 * docs_per_req] * 4,
                                tenant="noisy", priority="bulk",
                            )
                            noisy_results["answered"] += 1
                        except ServeHTTPError as e:
                            if e.status == 503 and e.shed:
                                noisy_results["expected_sheds"] += 1
                            else:
                                with lock:
                                    errors.append(f"noisy burst {k}: {e}")
                        except OSError as e:
                            # Recorded, not raised: an unhandled error
                            # here would kill client 0 and silently skip
                            # the refit leg it also drives.
                            with lock:
                                errors.append(f"noisy burst {k}: {e}")
                    continue
                if ci == 0 and r == refit_round:
                    est = LanguageDetector(langs, [1, 2], 100)
                    docs = (
                        ["aaa bab caa"] * 6 + ["xxy yxy xyy"] * 6
                        + ["dcd cdd dzz"] * 6
                    )
                    labs = ["l0"] * 6 + ["l1"] * 6 + ["l2"] * 6
                    ar = zoo.auto_refit(
                        refit_tenant, est,
                        refit_every_batches=1, final_refit=False,
                    )
                    ar.run(
                        [Table({"lang": labs, "fulltext": docs})],
                        max_batches=1,
                    )
                    refit_version[0] = zoo.version(refit_tenant)
                    ids = ar.last_model._get_runner().predict_ids(eval_docs)
                    with lock:
                        expected[(refit_tenant, refit_version[0])] = [
                            langs[int(x)] for x in ids
                        ]
                    continue
                # Stride through the tenant population: every client touches
                # every tenant over the run, far past the residency cap.
                tenant = tenants[(ci + r * n_clients) % n_tenants]
                one_request(tenant, f"round {r}")

        threads = [
            threading.Thread(target=drive, args=(ci,)) for ci in range(n_clients)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)

        # Victim shed check over the PERSISTENT per-tenant counters, not the
        # current queue stats: a reload builds a fresh AdmissionQueue whose
        # local tallies restart at 0, so under eviction churn only the
        # `zoo/shed/<tenant>` counters can prove a victim never shed.
        zoo_health = zoo.healthz()
        pre_stop_counters = REGISTRY.snapshot()["counters"]
        victim_sheds = sum(
            int(pre_stop_counters.get(f"zoo/shed/{name}", 0))
            for name in tenants
        )
        cold_reloads = sum(
            max(0, block["loads"] - 1)
            for block in zoo_health["tenants"].values()
        )
        server.stop()
        server = None  # stopped cleanly: the finally must not re-stop

        # Parity: every response must match its own tenant's direct runner
        # for the version that answered — a cross-tenant answer is a
        # mismatch by construction (distinct signatures).
        checked = mismatches = 0
        versions_served: dict[str, set] = {}
        for tenant, version, lo, got in responses:
            want = expected.get((tenant, version))
            checked += 1
            if want is None or got != want[lo:lo + docs_per_req]:
                mismatches += 1
            versions_served.setdefault(tenant, set()).add(version)
        parity = 1.0 if checked and mismatches == 0 else (
            round(1.0 - mismatches / checked, 6) if checked else 0.0
        )
        swapped = sum(
            1 for t in tenants if zoo.version(t) != "v1"
        )

        snap = REGISTRY.snapshot()
        counters = snap["counters"]
        noisy_sheds = int(counters.get("zoo/shed/noisy", 0))
        result = {
            "smoke_zoo": True,
            "trimmed": trimmed,
            "tenants": n_tenants,
            "resident_cap": resident_cap,
            "clients": n_clients,
            "answered": len(responses),
            "dropped_responses": len(errors),
            "errors": errors[:5],
            "signatures_distinct": distinct_ok,
            "argmax_parity": parity,
            "evictions": int(counters.get("zoo/evictions", 0)),
            "cold_loads": int(counters.get("zoo/cold_loads", 0)),
            "cold_reloads": cold_reloads,
            "cross_tenant_rejects": int(
                counters.get("zoo/cross_tenant_rejects", 0)
            ),
            "noisy": {
                "noisy_sheds": noisy_sheds,
                "expected_sheds": noisy_results["expected_sheds"],
                "burst_answered": noisy_results["answered"],
                "victim_sheds": victim_sheds,
            },
            "refit": {
                "tenant": refit_tenant,
                "version": refit_version[0],
                "swapped_tenant_versions": swapped,
            },
            "residency": zoo_health["residency"],
            "telemetry": telemetry_block(path),
        }
        result["ok"] = bool(
            not errors
            and distinct_ok
            and checked > 0
            and parity == 1.0
            and result["cross_tenant_rejects"] == 0
            and result["evictions"] >= 1
            and cold_reloads >= 1
            and noisy_sheds >= 1
            and noisy_results["expected_sheds"] >= 1
            and victim_sheds == 0
            and refit_version[0] == "v2"
            and swapped == 1
            and versions_served.get(refit_tenant, set()) >= {"v1"}
        )
        return result
    finally:
        # Any mid-run failure must not leak the HTTP server, the
        # per-tenant batcher threads, or the telemetry sink into
        # the caller's process (tier-1 runs the trimmed variant).
        if server is not None:
            try:
                server.stop()
            except Exception:
                pass
        REGISTRY.remove_sink(sink)


def fit_scaling() -> dict:
    """Fit-scaling leg: device fit docs/s and collect bytes on a 1-device
    vs an 8-virtual-device CPU mesh (the test substrate's geometry).

    Each leg runs in a subprocess because the virtual device count is an
    XLA startup flag. The capture records the before/after collect story:
    ``full_table_bytes`` is what the host finalize used to pull per fit,
    ``fit_collect_bytes`` is what the winner-rows-only device finalize
    moves now, ``collect_ratio`` their quotient — on BOTH geometries (the
    8-device leg's finalize is the cross-shard collective merge). Gated
    on parity with the host fit and on the ratio staying well under 1.
    """
    import subprocess

    results: dict[str, dict] = {}
    for n in (1, 8):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        base = env.get("XLA_FLAGS", "")
        base = " ".join(
            p for p in base.split()
            if "xla_force_host_platform_device_count" not in p
        )
        env["XLA_FLAGS"] = (
            f"{base} --xla_force_host_platform_device_count={n}".strip()
        )
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--fit-scaling-probe", str(n)],
            capture_output=True, text=True, timeout=1200, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        if proc.returncode != 0:
            tail = (proc.stderr or proc.stdout).strip().splitlines()[-3:]
            results[str(n)] = {"error": " | ".join(tail)}
            continue
        results[str(n)] = json.loads(proc.stdout.strip().splitlines()[-1])
    ok = all(
        r.get("parity_vs_host") and r.get("collect_ratio", 1.0) < 0.5
        for r in results.values()
    )
    one, eight = results.get("1", {}), results.get("8", {})
    out = {
        "fit_scaling": results,
        "scaling_1_to_8": (
            round(eight["fit_docs_per_s"] / one["fit_docs_per_s"], 3)
            if one.get("fit_docs_per_s") and eight.get("fit_docs_per_s")
            else None
        ),
        "ok": ok,
    }
    return out


# ------------------------------------------------------------ per config ----
CONFIGS = {
    # cap: ship maxScoreBytes=256 on the headline config — language identity
    # saturates within a few hundred bytes (the short-doc legs show full
    # accuracy at 120B), and the wire is this config's binding wall
    # (docs/PERFORMANCE.md §1): a 256B cap ships ~6× fewer bytes at 1.5KB
    # mean doc length. The full-length accuracy and compute rate are
    # reported alongside (accuracy_fulllen / cap_accuracy_delta /
    # compute_docs_per_s_fulllen), and the parity gate compares against the
    # reference semantics on the SAME truncated bytes.
    1: dict(label="config1 bigram en/de/fr", n_langs=3, gram_lengths=[2],
            k=2000, vocab="exact", docs=20000, baseline_docs=1000,
            train_per_lang=60, cap=256),
    2: dict(label="config2 n=1..3, 10 European languages", n_langs=10,
            gram_lengths=[1, 2, 3], k=3000, vocab="exact", docs=20000,
            baseline_docs=400, train_per_lang=60),
    3: dict(label="config3 n=1..5, 50 languages (CLD2-scale, exact/cuckoo)",
            n_langs=50, gram_lengths=[1, 2, 3, 4, 5], k=1000, vocab="exact",
            docs=8000, baseline_docs=120, train_per_lang=40),
    4: dict(label="config4 streaming micro-batch (10 languages, n=1..3)",
            n_langs=10, gram_lengths=[1, 2, 3], k=3000, vocab="exact",
            docs=10000, baseline_docs=200, train_per_lang=60, streaming=True),
    # Config 5 ships the cap too: fastText itself scores bounded input, and
    # this config is fully wire-bound (6k docs × 1.5KB ≈ 9MB/pass). Zero
    # accuracy delta and 1.0 label agreement with full-length scoring;
    # end-to-end the cap measured 3.36× on a 4k-doc probe and 3.5× on the
    # full bench capture (30,776 vs 8,782 docs/s, same-session weather).
    5: dict(label="config5 n=1..5 hashed 2^20, 176 languages (fastText-scale)",
            n_langs=176, gram_lengths=[1, 2, 3, 4, 5], k=400, vocab="hashed",
            docs=6000, baseline_docs=50, train_per_lang=30, cap=256),
}

_model_cache: dict[tuple, object] = {}


def fit_model(cfg):
    from spark_languagedetector_tpu import LanguageDetector, Table

    key = (cfg["n_langs"], tuple(cfg["gram_lengths"]), cfg["k"], cfg["vocab"])
    if key in _model_cache:
        return _model_cache[key]
    langs = language_names(cfg["n_langs"])
    docs, labels = make_corpus(langs, cfg["train_per_lang"] * len(langs), seed=1)
    det = LanguageDetector(langs, cfg["gram_lengths"], cfg["k"]).set_vocab_mode(
        cfg["vocab"]
    ).set_hash_bits(20)
    model = det.fit(Table({"lang": labels, "fulltext": docs}))
    _model_cache[key] = model
    return model


def _baseline_scorer(model):
    """Per-row reference-semantics scorer closure for this model."""
    langs = model.profile.languages
    spec = model.profile.spec
    if spec.mode == "exact" and max(spec.gram_lengths) <= 3:
        gram_map = {g: list(v) for g, v in model.gram_probabilities.items()}
        return lambda t: baseline_score(
            t, gram_map, len(langs), spec.gram_lengths
        )
    bucket_map = _bucket_map(model)
    return lambda t: baseline_score_ids(t, bucket_map, spec, len(langs))


def compute_baseline_labels(model, cfg, eval_docs):
    """(per-row argmax labels, subset) — the parity gate's reference side.

    >= 1000 docs (or the whole eval set if smaller). This is the slow
    pure-Python part (~30-70s for the long-gram configs), so run_config
    overlaps it with the device warmup; only the LABELS are used from this
    pass — the timed denominators come from time_baselines, measured
    sequentially on an idle host.
    """
    n = int(
        os.environ.get(
            "BENCH_BASELINE_DOCS",
            max(cfg["baseline_docs"], min(1000, len(eval_docs))),
        )
    )
    if n <= 0:
        return None, [], None
    sub = eval_docs[:n]
    scorer = _baseline_scorer(model)
    return [int(np.argmax(scorer(t))) for t in sub], sub, scorer


def time_baselines(model, sub, scorer):
    """(per-row docs/s, numpy docs/s) measured sequentially (idle host).

    The per-row rate times a ~200-doc slice (stable enough; full-subset
    timing would re-pay the minutes the parity pass already spent), the
    numpy mirror times the whole subset (it is vectorized and cheap).
    ``scorer`` is the closure compute_baseline_labels already built (its
    gram/bucket tables are seconds of host work at vocab scale).
    """
    from spark_languagedetector_tpu.ops.score import score_batch_numpy

    if not sub:
        return None, None
    t_sub = sub[:200]
    t0 = time.perf_counter()
    for t in t_sub:
        scorer(t)
    t_base = time.perf_counter() - t0
    cw, cids = model.profile.host_arrays()
    spec = model.profile.spec
    t0 = time.perf_counter()
    score_batch_numpy([t.encode("utf-8") for t in sub], cw, cids, spec)
    t_np = time.perf_counter() - t0
    return len(t_sub) / t_base, len(sub) / t_np


_WIRE_SEQ = iter(range(1, 1 << 30))  # process-wide: probes never recur


def measure_wire_mbps():
    """h2d bandwidth probe: best-of-3 timed 4MB device_puts, RTT-corrected.

    Self-documents the relay's bandwidth weather in the artifact so a low
    end-to-end number can be read against the link, not the kernels (the
    tunneled wire swings 3-90MB/s across sessions with identical code).
    Each put is bounded by a scalar fetch; the fetch's round-trip is
    measured separately (a 1-byte put + the same fetch) and subtracted so
    a fast-but-high-RTT link is not misreported as slow.
    """
    import jax
    import jax.numpy as jnp

    try:
        rng = np.random.default_rng(0)
        # Every probe payload must be unique — including ACROSS calls (one
        # per config in the same process): the relay can serve a repeated
        # (executable, args) pair from cache (docs/PERFORMANCE.md §5), and
        # 1-byte random payloads collide with probability ~1/256 per pair.
        # The module-level counter stamps every buffer, so neither the RTT
        # probes nor the seeded 4MB payloads ever recur process-wide.

        def timed_put(nbytes):
            if nbytes <= 8:
                buf = np.frombuffer(
                    np.int64(next(_WIRE_SEQ)).tobytes(), np.uint8
                ).copy()
            else:
                buf = rng.integers(0, 256, (nbytes,), np.uint8)
                buf[:8] = np.frombuffer(
                    np.int64(next(_WIRE_SEQ)).tobytes(), np.uint8
                )
            t0 = time.perf_counter()
            dev = jax.device_put(buf)
            # A scalar reduce + fetch bounds the put's completion.
            float(jnp.sum(dev[:: 1 << 18].astype(jnp.int32)))
            return time.perf_counter() - t0

        timed_put(4 << 20)  # warm allocator + compile, discarded
        timed_put(8)  # warm the RTT probe's own (shape, executable), discarded
        rtt = min(timed_put(8) for _ in range(3))
        best = min(timed_put(4 << 20) for _ in range(3))
        if best - rtt <= 1e-3:
            # RTT swallowed the whole transfer window — any division here
            # reports an absurd rate; flag the measurement as unusable.
            return None
        return round((4 << 20) / (best - rtt) / 1e6, 1)
    except Exception:
        return None


def measure_compute_only(model, eval_docs):
    """Device docs/s with operands already resident — no host->device wire.

    Measures at exactly the production shape: ``batch_size`` rows (corpus
    tiled if shorter) at the eval docs' own length bucket, so the rate is
    directly comparable to ``value``. The relay can serve repeated
    identical (executable, args) executions from a cache
    (docs/PERFORMANCE.md §5), so every timed dispatch uses a buffer the
    relay has never executed: 13 row-rotations of the packed batch
    (identical compute cost, distinct contents), one spent on warmup and
    never timed, the rest dispatched exactly once each across 3 reps.
    """
    import jax

    from spark_languagedetector_tpu.ops.encoding import bucket_length

    runner = model._get_runner()
    if runner.mesh is not None:
        return None  # single-device measurement only
    docs_b = [t.encode("utf-8") for t in eval_docs]
    if runner.max_score_bytes:
        from spark_languagedetector_tpu.ops.encoding import truncate_utf8

        docs_b = [truncate_utf8(d, runner.max_score_bytes) for d in docs_b]
    pad_to = bucket_length(max(len(d) for d in docs_b), runner.length_buckets)
    # Production row count: the runner's own bucket-cap policy, so the
    # timed shape is one the runner actually dispatches for this corpus's
    # length bucket.
    from spark_languagedetector_tpu.api.runner import rows_for_bucket

    rows = rows_for_bucket(pad_to, runner.batch_size, runner.batch_bytes)
    while len(docs_b) < rows:  # tile short corpora up to production size
        docs_b = docs_b + docs_b
    docs_b = [d[:pad_to] for d in docs_b[:rows]]
    batch_np, lengths_np = runner._pack(docs_b, pad_to)

    def rotation(g):
        # Tiling by doubling can leave the batch row-periodic (period <
        # 13), which would re-align some rotations into identical buffers
        # and re-enable the relay result cache; stamping the rotation index
        # into one byte makes every buffer distinct at identical compute
        # cost (same shapes, same op graph — only the timed value matters).
        rb = np.roll(batch_np, g, axis=0)
        rb[0, 0] = np.uint8(g + 1)
        return (
            jax.device_put(rb, runner.device),
            jax.device_put(np.roll(lengths_np, g), runner.device),
        )

    groups = [rotation(g) for g in range(13)]
    # Warm compile + first execution on the one rotation the loop never
    # times (its (args, executable) pair must not recur).
    wb, wl = groups[12]
    np.asarray(runner._dispatch_batch(wb, wl, None, runner.device))
    best_rate = 0.0
    for rep in range(3):
        t0 = time.perf_counter()
        acc = None
        for g in range(rep * 4, rep * 4 + 4):
            b, l = groups[g]
            s = runner._dispatch_batch(b, l, None, runner.device)
            acc = s.sum() if acc is None else acc + s.sum()
        float(np.asarray(acc))
        best_rate = max(best_rate, 4 * rows / (time.perf_counter() - t0))
    return best_rate


def run_config(num: int, deadline: float | None = None) -> dict:
    """One config's full measurement. ``deadline`` (perf_counter value) gates
    the ADDITIVE legs only — accuracy legs and the config-5 hashed-vs-exact
    comparison are skipped with a marker when the soft budget is nearly
    spent, so the core metrics (value + parity gate + denominators) always
    complete for every config the budget admits at all."""
    from concurrent.futures import ThreadPoolExecutor

    cfg = CONFIGS[num]
    telemetry_jsonl = telemetry_setup()
    model = fit_model(cfg)
    langs = language_names(cfg["n_langs"])
    n_docs = int(os.environ.get("BENCH_DOCS", cfg["docs"]))
    eval_docs, eval_labels = make_corpus(langs, n_docs, seed=2)
    eval_bytes = sum(len(d.encode()) for d in eval_docs)

    # maxScoreBytes configs: the parity gate must compare reference
    # semantics on the SAME truncated bytes the device scores, so the
    # baseline labels are computed over boundary-safe-truncated docs (the
    # TIMED denominators still score the full docs — the reference always
    # does, LanguageDetectorModel.scala:139-152).
    cap = cfg.get("cap")
    if cap:
        from spark_languagedetector_tpu.ops.encoding import truncate_utf8

        parity_docs = [
            truncate_utf8(d.encode("utf-8"), cap).decode("utf-8")
            for d in eval_docs
        ]
    else:
        parity_docs = eval_docs

    # The parity-label pass (~30-70s of pure-Python scoring at 1000 docs
    # for the long-gram configs) overlaps the device warmup: jit compiles
    # are remote-compile HTTP waits here, so the GIL is mostly free. Its
    # TIMING is never used — denominators come from time_baselines after
    # the join, sequentially, so neither side's measurement shares the
    # machine with the other.
    pool = ThreadPoolExecutor(max_workers=1)
    baseline_fut = pool.submit(compute_baseline_labels, model, cfg, parity_docs)
    try:

        if cfg.get("streaming"):
            from spark_languagedetector_tpu import Table
            from spark_languagedetector_tpu.stream.microbatch import (
                memory_source,
                run_stream,
            )

            rows = [{"fulltext": t} for t in eval_docs]
            sink_rows = []
            run_stream(  # warmup: compile every shape outside the timed window
                model, memory_source(rows, 8192), lambda t: None,
                prefetch=6, workers=4,
            )
            base_pred, sub, scorer = baseline_fut.result()
            full_sub = sub  # streaming configs never cap
            baseline_dps, baseline_np_dps = time_baselines(model, sub, scorer)
            times = []
            batch_lat: list[list[float]] = []
            # Streaming is transfer-bound like the other short-gram configs
            # and gets extra passes the same way (7 here: streaming passes
            # run the whole corpus through the engine, so they are slower
            # than the batch path's and one fewer keeps the budget).
            # Four transform workers with a deep prefetch
            # keep the bursty wire saturated across batches (A/B on the
            # tunneled v5e: w2/p3 11.3k, w4/p6 24.9-25.2k rows/s in the same
            # window; w6+/deeper plateaus). 8192-row source batches beat 4096
            # consistently (fewer transform calls, deeper in-call pipelining;
            # 19.9k vs 13.7k rows/s on a cold wire, ~5% ahead when warm).
            for _ in range(7 if max(cfg["gram_lengths"]) <= 3 else 3):
                lat: list[tuple[float, str | None]] = []
                t0 = time.perf_counter()
                q = run_stream(
                    model, memory_source(rows, 8192), sink_rows.append,
                    prefetch=6, workers=4,
                    # Per-batch (seconds, trace id): the engine mints one
                    # request trace per source batch, so the slowest batch
                    # of the whole config is directly greppable in the
                    # JSONL capture.
                    on_progress=lambda q, lat=lat: lat.append(
                        (q.last_batch_seconds, q.last_batch_trace_id)
                    ),
                )
                times.append(time.perf_counter() - t0)
                batch_lat.append(lat)
                sink_rows.clear()
            t_dev = min(times)
            all_lat = [entry for lat in batch_lat for entry in lat]
            slow_trace_s, slow_trace_id = (
                max(all_lat, key=lambda e: e[0]) if all_lat else (None, None)
            )
            # Per-batch latency percentiles from the best pass — the one
            # latency-shaped metric a micro-batch engine should publish
            # (VERDICT r4 #8). Batch latency here = transform-or-wait +
            # sink, i.e. the sink-visible stall per 8192-row micro-batch.
            best_lat = [s for s, _ in batch_lat[int(np.argmin(times))]]
            lat_p50 = float(np.percentile(best_lat, 50)) if best_lat else None
            lat_p95 = float(np.percentile(best_lat, 95)) if best_lat else None
            device_dps = n_docs / t_dev
            median_dps = n_docs / sorted(times)[len(times) // 2]
            # Parity gate for the streaming path: labels produced by the same
            # model.transform the engine drives, compared row-for-row against
            # the per-row baseline's argmax.
            parity = None
            if base_pred:
                out = model.transform(Table({"fulltext": list(sub)}))
                dev_labels = list(out.column(model.get_output_col()))
                parity = float(
                    np.mean([langs[p] == d for p, d in zip(base_pred, dev_labels)])
                )
            full = model.transform(Table({"fulltext": eval_docs}))
            accuracy = float(np.mean([
                a == b
                for a, b in zip(full.column(model.get_output_col()), eval_labels)
            ]))
        else:
            from spark_languagedetector_tpu.ops.encoding import texts_to_bytes

            runner = model._get_runner()
            docs_b = texts_to_bytes(eval_docs)
            # Warmup = one full pass, so every (batch, length-bucket) shape XLA
            # will see — including the ragged final batch — is compiled outside
            # the timed window. The timed pass is the LABEL pipeline (device
            # argmax, int32 ids fetched) — what the reference's transform
            # produces; score fetches of [N, L] floats would bill d2h wire the
            # product never pays.
            ids = runner.predict_ids(docs_b)
            accuracy_fulllen = compute_fulllen = None
            if cap:
                # The uncapped warmup doubles as the full-length reference:
                # its labels give accuracy_fulllen (for cap_accuracy_delta)
                # and the resident-operand rate at full doc length is kept
                # for round-over-round comparability before the cap is
                # applied to the runner.
                accuracy_fulllen = float(np.mean(
                    [langs[i] == want for i, want in zip(ids, eval_labels)]
                ))
                compute_fulllen = measure_compute_only(model, eval_docs)
                model.set("maxScoreBytes", cap)
                runner = model._get_runner()
                ids = runner.predict_ids(docs_b)  # capped-shape warmup
            base_pred, sub, scorer = baseline_fut.result()
            # Timed denominators always score the FULL docs (the reference
            # has no cap); parity labels used the truncated ones.
            full_sub = eval_docs[: len(sub)]
            baseline_dps, baseline_np_dps = time_baselines(model, full_sub, scorer)
            # Best of N timed passes: the device link (e.g. a tunneled TPU) has
            # bursty latency/bandwidth that can dominate a single pass; the best
            # pass is the closest observable to steady-state throughput. The
            # median is reported alongside so the burst variance is visible.
            # Transfer-bound configs (short gram lengths ⇒ compute hides
            # under the wire) get extra passes: each is ~0.5-1.5s and the
            # relay's stall windows last seconds, so more samples raise the
            # odds that min-time lands in clear weather.
            n_passes = 8 if max(cfg["gram_lengths"]) <= 3 else 4
            pass_times = []
            pass_traces = []
            # Each timed pass is one request: its trace id ties the pass
            # to every span it recorded in the JSONL capture, so the
            # artifact's slowest_trace_id points at a greppable offender.
            from spark_languagedetector_tpu.telemetry import (
                new_trace_id,
                trace_request,
            )

            for _ in range(n_passes):
                pass_tid = new_trace_id()
                t0 = time.perf_counter()
                with trace_request(pass_tid):
                    ids = runner.predict_ids(docs_b)
                pass_times.append(time.perf_counter() - t0)
                pass_traces.append(pass_tid)
            t_dev = min(pass_times)
            slow_trace_id = pass_traces[int(np.argmax(pass_times))]
            slow_trace_s = max(pass_times)
            device_dps = n_docs / t_dev
            median_dps = n_docs / sorted(pass_times)[len(pass_times) // 2]
            parity = None
            if base_pred:
                dev_pred = ids[: len(sub)].tolist()
                parity = float(np.mean([a == b for a, b in zip(base_pred, dev_pred)]))
            accuracy = float(np.mean(
                [langs[i] == want for i, want in zip(ids, eval_labels)]
            ))

        if parity is not None and parity < 1.0:
            raise SystemExit(
                f"accuracy parity violated on {cfg['label']}: {parity:.4f} — "
                "device argmax disagrees with the reference-semantics baseline; "
                "refusing to report perf"
            )

        import jax

        # Compiled reference-shape baseline (vs_cpp): timed after the device
        # passes so the host is idle. For exact configs the C++ map is the
        # model's own gram map, so its labels must agree with the per-row
        # Python baseline exactly (same map, same accumulation order, both
        # in double) — reported as cpp_agreement and ENFORCED below: a
        # semantics drift in refscorer.cpp would silently skew the headline
        # vs_cpp denominator.
        cpp_dps, cpp_mt_dps, cpp_labels, cpp_map_grams = (
            time_cpp_baseline(
                model, cfg, full_sub, label_docs=(sub if cap else None)
            )
            if sub
            else (None, None, None, None)
        )
        cpp_agree = None
        if cpp_labels is not None and base_pred:
            cpp_agree = float(np.mean(
                [a == b for a, b in zip(base_pred, cpp_labels.tolist())]
            ))
            if cpp_agree < 1.0 and model.profile.spec.mode == "exact":
                raise SystemExit(
                    f"C++ baseline disagreement on {cfg['label']}: "
                    f"{cpp_agree:.4f} — refscorer.cpp has drifted from the "
                    "per-row reference semantics; the vs_cpp denominator "
                    "would be wrong, refusing to report perf"
                )
        compute_dps = measure_compute_only(model, eval_docs)
        wire_mbps = measure_wire_mbps()
        result = {
            "metric": f"langid docs/sec/chip ({cfg['label']}, {jax.default_backend()})",
            "value": round(device_dps, 1),
            "unit": "docs/sec",
            "config": num,
            "median_docs_per_s": round(median_dps, 1),
            "baseline_kind": "python-per-row (reference hot-loop semantics)",
            "argmax_parity": parity,
            # Ground-truth label accuracy on the synthetic eval corpus —
            # the BASELINE metric's accuracy leg (parity above pins
            # equivalence to the reference semantics; this pins that both
            # actually detect the right language).
            "accuracy": round(accuracy, 4),
            "parity_docs": len(sub),
            "eval_docs": n_docs,
            "eval_mb": round(eval_bytes / 1e6, 1),
        }
        if wire_mbps is not None:
            result["wire_mbps"] = wire_mbps
        if compute_dps:
            # Conservative kernel rate: full-width docs (truncated to the widest
            # bucket), resident operands. End-to-end `value` can exceed it when
            # the real corpus is shorter than the bucket width.
            result["compute_docs_per_s"] = round(compute_dps, 1)
        if not cfg.get("streaming"):
            result["strategy"] = model._get_runner().strategy

        def budget_left(need_s: float) -> bool:
            return deadline is None or time.perf_counter() + need_s < deadline

        if cap:
            result["max_score_bytes"] = cap
            result["accuracy_fulllen"] = round(accuracy_fulllen, 4)
            result["cap_accuracy_delta"] = round(
                accuracy - accuracy_fulllen, 4
            )
            if compute_fulllen:
                result["compute_docs_per_s_fulllen"] = round(compute_fulllen, 1)
            # The cap's real cost case: code-switched docs, where the prefix
            # can be dominated by the minority language (clean docs show
            # zero delta down to 128B; mixed docs lose ~4pts at 256B —
            # measured round 5, the reason the default cap is conservative).
            # Scored here while the model is still capped; the uncapped leg
            # below provides the comparison, reported as cap_mixed_delta.
            pairs = [
                p for p in _CONFUSABLE_PAIRS if p[0] in langs and p[1] in langs
            ]
            # Additive leg: skips with the others when the budget is tight
            # (a new bucket shape can cost a 20-40s remote compile, and its
            # only consumer is the uncapped legs' delta below).
            if pairs and budget_left(180):
                from spark_languagedetector_tpu import Table as _T

                a, b = pairs[0]
                mixed = make_mixed_corpus(
                    a, b, 300, mean_len=400, frac_a=0.7, seed=11
                )
                out = model.transform(_T({"fulltext": mixed}))
                result["mixed_dominant_accuracy_capped"] = round(float(np.mean(
                    [v == a for v in out.column(model.get_output_col())]
                )), 4)

        # Additive legs (new shapes compile ~20-40s each through a remote-
        # compile tunnel): only when the soft budget has room, so a driver
        # on the default budget still gets every config's core metrics.
        # The cap comes OFF first: the legs compare device vs reference
        # semantics per leg, so both sides must score the same full docs
        # (the cap's own impact is already captured by cap_accuracy_delta);
        # it also keeps the legs comparable round-over-round.
        if cap:
            model.set("maxScoreBytes", None)
        if budget_left(120):
            result.update(accuracy_legs(model, cfg, langs, ref_scorer=scorer))
            if "mixed_dominant_accuracy_capped" in result and (
                "mixed_dominant_accuracy" in result
            ):
                result["cap_mixed_delta"] = round(
                    result["mixed_dominant_accuracy_capped"]
                    - result["mixed_dominant_accuracy"],
                    4,
                )
        else:
            result["accuracy_legs"] = "skipped (soft budget)"
        if num == 5:
            if budget_left(240):
                result.update(hashed_vs_exact(model, cfg, langs))
            else:
                result["hashed_vs_exact"] = "skipped (soft budget)"
        if num in (2, 3, 5):
            # Fit throughput (host vs device) at the three scales that
            # stress it: 10-lang n=1..3, 50-lang n=1..5, 176-lang hashed.
            if budget_left(240):
                result.update(fit_bench(cfg, langs))
            else:
                result["fit_bench"] = "skipped (soft budget)"
        if baseline_dps:
            result["vs_baseline"] = round(device_dps / baseline_dps, 2)
            result["vs_numpy"] = round(device_dps / baseline_np_dps, 2)
            result["baseline_docs_per_s"] = round(baseline_dps, 1)
            result["baseline_numpy_docs_per_s"] = round(baseline_np_dps, 1)
        if cpp_dps:
            result["vs_cpp"] = round(device_dps / cpp_dps, 2)
            result["baseline_cpp_docs_per_s"] = round(cpp_dps, 1)
            result["cpp_map_grams"] = cpp_map_grams
            if cpp_agree is not None:
                result["cpp_agreement"] = round(cpp_agree, 4)
        if cpp_mt_dps:
            result["vs_cpp_mt"] = round(device_dps / cpp_mt_dps, 2)
            result["baseline_cpp_mt_docs_per_s"] = round(cpp_mt_dps, 1)
            result["cpp_threads"] = usable_cpus()
        if cfg.get("streaming"):
            result["note"] = "rows/sec through run_stream incl. sink"
            if lat_p50 is not None:
                result["batch_latency_p50_s"] = round(lat_p50, 3)
                result["batch_latency_p95_s"] = round(lat_p95, 3)
                result["latency_batch_rows"] = 8192
        # Stage-level breakdown (cumulative through this config) + the JSONL
        # event-log path, so the BENCH artifact localizes a regression to a
        # stage instead of reporting one opaque end-to-end number. The
        # slowest request's trace id makes the worst pass/batch greppable
        # in that JSONL (and renderable via the telemetry.tracing CLI).
        result["telemetry"] = telemetry_block(telemetry_jsonl)
        if slow_trace_id is not None:
            result["telemetry"]["slowest_trace_id"] = slow_trace_id
            result["telemetry"]["slowest_trace_s"] = round(slow_trace_s, 4)
        if num == 1:
            # Fused-megakernel + quantized-table leg (ROADMAP item 3).
            # Runs AFTER the telemetry block is assembled so its dispatch
            # spans (interpret-mode slow on the CPU substrate) never
            # dilute the main strategy's per-stage percentiles.
            if budget_left(240):
                result.update(
                    fused_leg(
                        model, cfg, langs, base_pred, sub, cpp_mt_dps,
                        eval_docs,
                    )
                )
            else:
                result["fused"] = "skipped (soft budget)"
        return result
    finally:
        # The model cache outlives this config: never leak the cap.
        if cap and model.is_set("maxScoreBytes"):
            model.set("maxScoreBytes", None)
        # Always reap the baseline thread — an exception during warmup
        # must not leave a GIL-grinding scorer polluting the next
        # config's timed measurements.
        pool.shutdown(wait=True)


def main():
    if "--smoke-telemetry" in sys.argv[1:]:
        # Telemetry smoke path: tiny CPU fit+score with the JSONL sink on,
        # one JSON line out (the report CLI renders the stage tree from the
        # printed jsonl path). Seconds, not minutes — safe anywhere.
        args = [a for a in sys.argv[1:] if a != "--smoke-telemetry"]
        flags = [a for a in args if a.startswith("-")]
        if flags or len(args) > 1:
            print(
                f"usage: python bench.py --smoke-telemetry [out.jsonl] "
                f"(got {args})",
                file=sys.stderr,
            )
            sys.exit(2)
        print(json.dumps(smoke_telemetry(args[0] if args else None)), flush=True)
        return
    if "--smoke-chaos" in sys.argv[1:]:
        # Chaos smoke path: scripted fault schedule on CPU, one JSON line
        # out with recovery counts + degraded-mode time share. The
        # fault-free-oracle parity check is a hard gate, like the main
        # bench's accuracy parity.
        args = [a for a in sys.argv[1:] if a != "--smoke-chaos"]
        flags = [a for a in args if a.startswith("-")]
        if flags or len(args) > 1:
            print(
                f"usage: python bench.py --smoke-chaos [out.jsonl] "
                f"(got {args})",
                file=sys.stderr,
            )
            sys.exit(2)
        result = smoke_chaos(args[0] if args else None)
        print(json.dumps(result), flush=True)
        if not result["oracle_match"]:
            print(
                "chaos smoke FAILED: " + "; ".join(result["mismatches"]),
                file=sys.stderr,
            )
            sys.exit(1)
        return
    if "--smoke-serve" in sys.argv[1:]:
        # Serving smoke path: in-process HTTP server + concurrent clients,
        # one mid-run hot-swap, one shed burst. Gates: bit-exact parity
        # per served version, zero dropped responses, demonstrated
        # coalescing, explicit shed rejections.
        args = [a for a in sys.argv[1:] if a != "--smoke-serve"]
        flags = [a for a in args if a.startswith("-")]
        if flags or len(args) > 1:
            print(
                f"usage: python bench.py --smoke-serve [out.jsonl] "
                f"(got {args})",
                file=sys.stderr,
            )
            sys.exit(2)
        result = smoke_serve(args[0] if args else None)
        print(json.dumps(result), flush=True)
        if not result["ok"]:
            print(
                "serve smoke FAILED: "
                + (
                    "; ".join(result["errors"])
                    or "gate (parity/dropped/coalescing/shed) not met"
                ),
                file=sys.stderr,
            )
            sys.exit(1)
        return
    if "--smoke-fleet" in sys.argv[1:]:
        # Fleet smoke path: 3 replicas behind the health-checked router,
        # concurrent socket clients, a scripted mid-run replica kill +
        # half-open re-admission, and a fleet-wide two-phase hot-swap.
        # Gates: zero dropped responses, argmax parity 1.0 per served
        # version, >=1 failover/ejection/re-admission, swap atomicity.
        args = [a for a in sys.argv[1:] if a != "--smoke-fleet"]
        flags = [a for a in args if a.startswith("-")]
        if flags or len(args) > 1:
            print(
                f"usage: python bench.py --smoke-fleet [out.jsonl] "
                f"(got {args})",
                file=sys.stderr,
            )
            sys.exit(2)
        result = smoke_fleet(args[0] if args else None)
        print(json.dumps(result), flush=True)
        if not result["ok"]:
            print(
                "fleet smoke FAILED: "
                + (
                    "; ".join(result["errors"])
                    or "gate (drop/parity/failover/ejection/readmission/"
                    "swap-atomicity) not met"
                ),
                file=sys.stderr,
            )
            sys.exit(1)
        return
    if "--smoke-storm" in sys.argv[1:]:
        # Storm smoke path: the storm-defense stack (deadline decay,
        # retry budget, hedged dispatch, query-of-death quarantine;
        # docs/RESILIENCE.md §7) against a live 3-replica fleet. Gates:
        # fleet survival, poison quarantined after <=K deaths + 422,
        # bounded retry amplification, parity 1.0, hedge p99 cut, zero
        # hedges under overload.
        args = [a for a in sys.argv[1:] if a != "--smoke-storm"]
        flags = [a for a in args if a.startswith("-")]
        if flags or len(args) > 1:
            print(
                f"usage: python bench.py --smoke-storm [out.jsonl] "
                f"(got {args})",
                file=sys.stderr,
            )
            sys.exit(2)
        result = smoke_storm(args[0] if args else None)
        print(json.dumps(result), flush=True)
        if not result["ok"]:
            print(
                "storm smoke FAILED: "
                + (
                    "; ".join(result["errors"])
                    or "gate (survival/quarantine/amplification/parity/"
                    "hedge/overload) not met"
                ),
                file=sys.stderr,
            )
            sys.exit(1)
        return
    if "--smoke-scale" in sys.argv[1:]:
        # Elastic-fleet smoke path: min=1/max=3 subprocess replicas +
        # autoscaler under a quiet->burst->quiet load ramp with a
        # mid-burst replica SIGKILL. Gates: replica count tracks the
        # ramp up AND down, a supervised restart observed, zero dropped
        # responses, argmax parity 1.0.
        args = [a for a in sys.argv[1:] if a != "--smoke-scale"]
        flags = [a for a in args if a.startswith("-")]
        if flags or len(args) > 1:
            print(
                f"usage: python bench.py --smoke-scale [out.jsonl] "
                f"(got {args})",
                file=sys.stderr,
            )
            sys.exit(2)
        result = smoke_scale(args[0] if args else None)
        print(json.dumps(result), flush=True)
        if not result["ok"]:
            print(
                "scale smoke FAILED: "
                + (
                    "; ".join(result["errors"])
                    or "gate (ramp-up/ramp-down/restart/drop/parity) "
                    "not met"
                ),
                file=sys.stderr,
            )
            sys.exit(1)
        return
    if "--smoke-spawn" in sys.argv[1:]:
        # Cold-start-plane smoke: bake the artifact, spawn the same
        # replica cold (empty compile cache) then warm, and gate the
        # prewarm handshake — warm warmup >= 3x faster, cache hits
        # observed in the warm child, baked loader used on both spawns,
        # zero spawn failures, first-dispatch parity 1.0.
        args = [a for a in sys.argv[1:] if a != "--smoke-spawn"]
        flags = [a for a in args if a.startswith("-")]
        if flags or len(args) > 1:
            print(
                f"usage: python bench.py --smoke-spawn [out.jsonl] "
                f"(got {args})",
                file=sys.stderr,
            )
            sys.exit(2)
        result = smoke_spawn(args[0] if args else None)
        print(json.dumps(result), flush=True)
        if not result["ok"]:
            print(
                "spawn smoke FAILED: "
                + (
                    "; ".join(result["errors"])
                    or "gate (warmup-ratio/cache-hits/baked-load/"
                    "spawn-failure/parity) not met"
                ),
                file=sys.stderr,
            )
            sys.exit(1)
        return
    if "--smoke-obs" in sys.argv[1:]:
        # Fleet-observability smoke path: 2 subprocess replicas with
        # per-process JSONL captures, the collector + SLO evaluator on
        # the autoscaler ticks, one induced shed burst, one scale-down.
        # Gates: aggregate == sum of per-replica scrapes (exact, incl.
        # the drained member), a stitched cross-process flow with
        # non-negative nesting slack, burn-rate trip AND clear, zero
        # scrape failures, zero drops, parity 1.0.
        args = [a for a in sys.argv[1:] if a != "--smoke-obs"]
        flags = [a for a in args if a.startswith("-")]
        if flags or len(args) > 1:
            print(
                f"usage: python bench.py --smoke-obs [out.jsonl] "
                f"(got {args})",
                file=sys.stderr,
            )
            sys.exit(2)
        result = smoke_obs(args[0] if args else None)
        print(json.dumps(result), flush=True)
        if not result["ok"]:
            print(
                "obs smoke FAILED: "
                + (
                    "; ".join(result["errors"])
                    or "gate (aggregate-exact/stitch/burn-trip-clear/"
                    "scrape-failures/drop/parity) not met"
                ),
                file=sys.stderr,
            )
            sys.exit(1)
        return
    if "--smoke-refit" in sys.argv[1:]:
        # Continuous-learning smoke: streaming accumulator updates,
        # checkpointed resume after a simulated kill, periodic refits
        # hot-swapped into a live registry — hard-gated on bit-exact
        # parity with a from-scratch fit and on the winner-rows-only
        # collect contract.
        args = [a for a in sys.argv[1:] if a != "--smoke-refit"]
        flags = [a for a in args if a.startswith("-")]
        if flags or len(args) > 1:
            print(
                f"usage: python bench.py --smoke-refit [out.jsonl] "
                f"(got {args})",
                file=sys.stderr,
            )
            sys.exit(2)
        result = smoke_refit(args[0] if args else None)
        print(json.dumps(result), flush=True)
        if not result["ok"]:
            print(
                "refit smoke FAILED: "
                + ("; ".join(result["errors"]) or "gate not met"),
                file=sys.stderr,
            )
            sys.exit(1)
        return
    if "--smoke-cache" in sys.argv[1:]:
        # Redundancy-eliminator smoke: Zipf-duplicated corpus through
        # batch, stream, and a 2-replica fleet with a mid-run hot-swap.
        # Gates: per-version parity exactly 1.0 with zero stale answers,
        # demonstrated cache hits + dedup savings, >=1.5x on the
        # duplicated corpus, <=3% overhead on all-unique traffic.
        args = [a for a in sys.argv[1:] if a != "--smoke-cache"]
        flags = [a for a in args if a.startswith("-")]
        if flags or len(args) > 1:
            print(
                f"usage: python bench.py --smoke-cache [out.jsonl] "
                f"(got {args})",
                file=sys.stderr,
            )
            sys.exit(2)
        result = smoke_cache(args[0] if args else None)
        print(json.dumps(result), flush=True)
        if not result["ok"]:
            print(
                "cache smoke FAILED: "
                + (
                    "; ".join(result["errors"])
                    or "gate (parity/staleness/hit-rate/speedup/overhead) "
                    "not met"
                ),
                file=sys.stderr,
            )
            sys.exit(1)
        return
    if "--smoke-zoo" in sys.argv[1:]:
        # Multi-tenant model-zoo smoke: ~32 tenants behind one zoo-backed
        # HTTP server, residency budget forcing evictions + cold reloads
        # mid-traffic, a noisy-neighbor burst at a small-quota tenant,
        # and one tenant-scoped refit hot-swap. Gates: per-tenant argmax
        # parity 1.0, zero cross-tenant answers, >=1 eviction AND cold
        # reload (leases never evicted), victim shed tallies all 0,
        # refit swaps exactly one tenant's version.
        args = [a for a in sys.argv[1:] if a != "--smoke-zoo"]
        flags = [a for a in args if a.startswith("-")]
        if flags or len(args) > 1:
            print(
                f"usage: python bench.py --smoke-zoo [out.jsonl] "
                f"(got {args})",
                file=sys.stderr,
            )
            sys.exit(2)
        result = smoke_zoo(args[0] if args else None)
        print(json.dumps(result), flush=True)
        if not result["ok"]:
            print(
                "zoo smoke FAILED: "
                + (
                    "; ".join(result["errors"])
                    or "gate (parity/cross-tenant/eviction/noisy-neighbor/"
                    "refit-scope) not met"
                ),
                file=sys.stderr,
            )
            sys.exit(1)
        return
    if "--smoke-segment" in sys.argv[1:]:
        # Segmentation smoke: block-structured code-switch corpus with
        # known boundaries through batch, stream, and a 2-replica fleet
        # with a mid-run hot-swap. Gates: span F1 >= 0.85, calibrated
        # ECE <= 0.10 and strictly better than uncalibrated, top-3
        # true-label hit >= 0.98 on mixed docs, zero stale/cross-mode
        # cache answers, whole-doc scores bit-identical.
        args = [a for a in sys.argv[1:] if a != "--smoke-segment"]
        flags = [a for a in args if a.startswith("-")]
        if flags or len(args) > 1:
            print(
                f"usage: python bench.py --smoke-segment [out.jsonl] "
                f"(got {args})",
                file=sys.stderr,
            )
            sys.exit(2)
        result = smoke_segment(args[0] if args else None)
        print(json.dumps(result), flush=True)
        if not result["ok"]:
            print(
                "segment smoke FAILED: "
                + (
                    "; ".join(result["errors"])
                    or "gate (F1/ECE/top-k/staleness/whole-doc pin) not met"
                ),
                file=sys.stderr,
            )
            sys.exit(1)
        return
    if "--smoke-tune" in sys.argv[1:]:
        # Autotuner smoke path: untuned capture → exec.tune → tuned re-run.
        # Gates: strictly lower aggregate padding waste, argmax parity 1.0,
        # tuned lattice within the compile-shape budget.
        args = [a for a in sys.argv[1:] if a != "--smoke-tune"]
        flags = [a for a in args if a.startswith("-")]
        if flags or len(args) > 1:
            print(
                f"usage: python bench.py --smoke-tune [out.jsonl] "
                f"(got {args})",
                file=sys.stderr,
            )
            sys.exit(2)
        result = smoke_tune(args[0] if args else None)
        print(json.dumps(result), flush=True)
        if not result["ok"]:
            print(
                "tune smoke FAILED: "
                + ("; ".join(result["errors"]) or "gate not met"),
                file=sys.stderr,
            )
            sys.exit(1)
        return
    if "--smoke-wire" in sys.argv[1:]:
        # Device-encode wire smoke: all-unique short docs A/B'd host-pack
        # vs device-encode. Gates: bit-exact parity on gather + fused
        # (knob and DocBlock tiers), >=2x wire bytes/doc reduction,
        # >=1.3x end-to-end all-unique speedup, degraded ladder falls to
        # the host-pack rung under a persistent score/pack fault with
        # scores bit-identical.
        args = [a for a in sys.argv[1:] if a != "--smoke-wire"]
        flags = [a for a in args if a.startswith("-")]
        if flags or len(args) > 1:
            print(
                f"usage: python bench.py --smoke-wire [out.jsonl] "
                f"(got {args})",
                file=sys.stderr,
            )
            sys.exit(2)
        result = smoke_wire(args[0] if args else None)
        print(json.dumps(result), flush=True)
        if not result["ok"]:
            print(
                "wire smoke FAILED: "
                + (
                    "; ".join(result["errors"])
                    or "gate (parity/wire-shrink/speedup/degraded-ladder) "
                    "not met"
                ),
                file=sys.stderr,
            )
            sys.exit(1)
        return
    if "--fit-scaling-probe" in sys.argv[1:]:
        # Child half of --fit-scaling (device count is an XLA startup
        # flag, so each geometry needs its own process).
        idx = sys.argv.index("--fit-scaling-probe")
        n = int(sys.argv[idx + 1])
        print(json.dumps(fit_scaling_probe(n)), flush=True)
        return
    if "--fit-scaling" in sys.argv[1:]:
        # Fit-scaling leg: 1-device vs 8-device CPU mesh fit throughput +
        # the before/after collect-bytes contract on both geometries.
        result = fit_scaling()
        print(json.dumps(result), flush=True)
        if not result["ok"]:
            print(
                "fit scaling FAILED: parity or collect-ratio gate not met",
                file=sys.stderr,
            )
            sys.exit(1)
        return
    order = [
        int(c)
        for c in os.environ.get("BENCH_CONFIGS", "2,3,4,5,1").split(",")
        if c.strip()
    ]
    # Soft wall-clock budget: a full five-config run is dominated by one-off
    # jit compiles (~6 min through a remote-compile tunnel). If a driver
    # enforces a timeout, the headline config (last in the list) must still
    # run — so once the budget is spent, intermediate configs are skipped
    # (noted on stderr) and the run jumps straight to the final config.
    # Default sized to the full five-config run with the round-5 additive
    # legs (fit benches, hard-corpus legs): ~20-25 min through the tunnel.
    # Round 4's driver tolerated a ~25-minute capture; the summary line
    # still prints before the hw suite so a harder cut cannot lose it.
    budget_s = float(os.environ.get("BENCH_SOFT_BUDGET_S", "1500"))
    t_start = time.perf_counter()
    deadline = t_start + budget_s
    failures = 0
    summary: dict[int, dict] = {}
    for i, num in enumerate(order):
        last = i == len(order) - 1
        if not last and time.perf_counter() - t_start > budget_s:
            print(
                json.dumps({"config": num, "skipped": "soft time budget"}),
                file=sys.stderr,
                flush=True,
            )
            summary[num] = {"skipped": "soft time budget"}
            continue
        try:
            result = run_config(num, deadline=deadline)
            print(json.dumps(result), flush=True)
            summary[num] = {
                k: result[k]
                for k in (
                    "value", "vs_baseline", "vs_numpy", "vs_cpp", "vs_cpp_mt",
                    "argmax_parity", "accuracy", "shortdoc_accuracy",
                    "shortdoc_ref", "noisy_accuracy", "noisy_ref",
                    "confusable_accuracy", "confusable_ref",
                    "mixed_dominant_accuracy", "mixed_dominant_ref",
                    "codeswitch90_accuracy", "codeswitch90_ref",
                    "hashed_vs_exact_agreement",
                    "hashed_vs_exact_shortdoc_delta",
                    "fit_docs_per_s_host", "fit_docs_per_s_device",
                    "fit_wire_mb", "fit_collect_bytes", "fit_collect_ratio",
                    "fit_compute_docs_per_s",
                    "fit_device_mismatch", "max_score_bytes",
                    "accuracy_fulllen", "cap_accuracy_delta",
                    "cap_mixed_delta", "compute_docs_per_s_fulllen",
                    "batch_latency_p50_s", "batch_latency_p95_s",
                    "compute_docs_per_s", "wire_mbps", "fused",
                )
                if k in result
            }
        except SystemExit:
            raise
        except Exception as e:  # keep later configs (incl. headline) alive
            failures += 1
            print(
                json.dumps(
                    {"config": num, "error": f"{type(e).__name__}: {e}"}
                ),
                file=sys.stderr,
                flush=True,
            )
            summary[num] = {"error": f"{type(e).__name__}: {e}"}
    # The driver stores only the stdout TAIL; per-config lines can be
    # truncated off the top (config 2 was lost from BENCH_r03.json). This
    # final compact line repeats every config's key numbers so the most
    # size-limited artifact in the loop survives a 4KB cut. It mirrors the
    # headline config's metric/value/unit at top level so a driver that
    # parses only the last stdout line still reads the headline number.
    # Printed BEFORE the hardware suite: that suite takes minutes and
    # reports to stderr only, so a driver timeout during it must not cost
    # the summary (it stays the last stdout line either way).
    final = dict(summary.get(order[-1], {})) if order else {}
    final.setdefault("metric", "langid docs/sec/chip (headline, config "
                     f"{order[-1] if order else '?'})")
    final.setdefault("unit", "docs/sec")
    # Read-only: the configs' telemetry_setup already attached the sink
    # (resetting aggregates here would wipe nothing useful but attaching a
    # fresh never-written sink on the all-configs-failed path would).
    try:
        from spark_languagedetector_tpu.telemetry import REGISTRY

        for sink in REGISTRY.sinks:
            if getattr(sink, "kind", "") == "jsonl":
                final["telemetry_jsonl"] = sink.path
                break
    except Exception:
        pass
    final["summary"] = summary
    print(json.dumps(final, separators=(",", ":")), flush=True)
    remaining = budget_s - (time.perf_counter() - t_start)
    run_tpu_hw_tests(remaining)
    if failures:
        sys.exit(1)


def run_tpu_hw_tests(
    remaining_budget_s: float = 300.0,
    test_path: str = "tests/test_tpu_hw.py",
):
    """Opt-in real-hardware Mosaic parity suite, after the headline config.

    Runs with SLD_TPU_TESTS=1 so the opt-in tests in tests/test_tpu_hw.py
    execute on the actual chip once per bench run. Reports to STDERR only —
    stdout's last line must stay the headline config's JSON (drivers
    tail-parse it).

    INCREMENTAL: the suite runs as one pytest subprocess whose verbose
    output is streamed line by line; every finished test emits its own
    stderr JSON line immediately, and when the budget expires the
    subprocess is killed but every already-finished result is kept — the
    final summary is ``{"passed": k, "of": n, ...}``, never an
    all-or-nothing "timeout" (round 4's defect: one slow compile voided
    the whole suite's results). The reference's analog is granular,
    individually-reported tests (build.sbt:13,19 unit/it configs).

    The subprocess needs a device stack that admits a second client while
    this process holds the chip (true of the axon relay here). On a
    co-located single-client libtpu, run the suite standalone instead:
    SLD_TPU_TESTS=1 pytest tests/test_tpu_hw.py.

    Default policy: opportunistic — the suite runs whenever the bench just
    completed on a healthy chip AND enough soft budget remains (>= 60s);
    SLD_TPU_TESTS=1 forces it, SLD_TPU_TESTS=0 disables it.
    """
    flag = os.environ.get("SLD_TPU_TESTS", "")
    if flag == "0":
        return
    if flag != "1" and remaining_budget_s < 60:
        return
    import re
    import subprocess
    import threading

    # A cold run costs ~4-6 min of remote-tunnel compiles. Forced runs get
    # a generous fixed budget; opportunistic runs get whatever soft budget
    # remains — truncation now costs only the unfinished tests.
    timeout_s = float(os.environ.get("SLD_TPU_TESTS_TIMEOUT_S", "0")) or (
        720.0 if flag == "1" else max(60.0, remaining_budget_s)
    )
    here = os.path.dirname(os.path.abspath(__file__))
    t_start = time.perf_counter()
    # -u: unbuffered child stdout so each test's verdict line arrives as it
    # finishes, not when the pipe buffer fills.
    proc = subprocess.Popen(
        [
            sys.executable, "-u", "-m", "pytest", test_path,
            "-v", "--tb=line", "-p", "no:cacheprovider",
        ],
        cwd=here,
        env={**os.environ, "SLD_TPU_TESTS": "1"},
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        text=True,
    )
    # Match on the target's file basename: pytest prints nodeids relative
    # to its rootdir (possibly with ../ segments), so the path as passed
    # may not appear. A ::selector target matches on its file component; a
    # directory target falls back to the generic "<file>.py::name STATUS"
    # shape.
    file_part = test_path.split("::", 1)[0].rstrip("/")
    base = os.path.basename(file_part)
    name_prefix = re.escape(base) if base.endswith(".py") else r"[\w./-]*\.py"
    verdict_re = re.compile(
        name_prefix + r"::(\S+)\s+(PASSED|FAILED|ERROR|SKIPPED)"
    )
    collected_re = re.compile(r"collecting.*\scollected\s+(\d+)\s+item|^collected\s+(\d+)\s+item")
    results: dict[str, str] = {}
    n_collected = [0]
    last_done = [t_start]

    def pump():
        for line in proc.stdout:
            m = collected_re.search(line)
            if m:
                n_collected[0] = int(m.group(1) or m.group(2))
            m = verdict_re.search(line.strip())
            if m:
                name, status = m.group(1), m.group(2).lower()
                now = time.perf_counter()
                results[name] = status
                print(
                    json.dumps(
                        {
                            "tpu_hw_test": name,
                            "status": status,
                            "seconds": round(now - last_done[0], 1),
                        }
                    ),
                    file=sys.stderr,
                    flush=True,
                )
                last_done[0] = now

    reader = threading.Thread(target=pump, daemon=True)
    reader.start()
    try:
        proc.wait(timeout=timeout_s)
        timed_out = False
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait()
        timed_out = True
    reader.join(timeout=10)
    counts = {"passed": 0, "failed": 0, "error": 0, "skipped": 0}
    for status in results.values():
        counts[status] = counts.get(status, 0) + 1
    summary = {
        "passed": counts["passed"],
        "of": max(n_collected[0], len(results)),
        "seconds": round(time.perf_counter() - t_start, 1),
    }
    if counts["failed"] or counts["error"]:
        summary["failed"] = counts["failed"] + counts["error"]
    if counts["skipped"]:
        summary["skipped"] = counts["skipped"]
    if timed_out:
        summary["budget_expired"] = True
    elif proc.returncode not in (0, None):
        # A nonzero exit with no per-test verdicts (collection/import
        # error, pytest crash) must not read as a clean empty run.
        summary["pytest_exit"] = proc.returncode
        if not results:
            summary["suite_error"] = True
    print(json.dumps({"tpu_hw_tests": summary}), file=sys.stderr, flush=True)


if __name__ == "__main__":
    main()
