"""Benchmark: langid docs/sec/chip vs a per-row CPU scoring baseline.

Covers all five BASELINE.md configs in one invocation, printing ONE JSON
line per config (the headline north-star config 1 is printed LAST):

  1. bigram (n=2) byte model, 3 languages (en/de/fr)           — exact
  2. n=1..3 mixed-gram model, 10 European languages            — exact
  3. n=1..5, 50-language profile matrix (CLD2-scale)           — exact (cuckoo)
  4. streaming micro-batch langid (run_stream + memory source) — config-2 model
  5. 176-language fastText-lid parity, n=1..5 hashed 2^20      — hashed exact12

Corpora are synthetic Wikipedia-like documents (~1.5KB each): the first ten
languages use real word lists, the rest procedurally generated per-language
vocabularies (distinct letter subsets + word shapes). BASELINE names
Wikipedia/CommonCrawl dumps; none are available in this zero-egress image,
so the baseline is *measured, not cited* (BASELINE.md) on the same synthetic
corpus for both sides.

Four baseline denominators per config, reported side by side:
  * ``vs_cpp`` / ``baseline_cpp_docs_per_s`` — a compiled per-row scorer
    with the reference hot loop's exact shape (native/refscorer.cpp:
    hash-map probe per window + double axpy + argmax, -O3, one thread).
    Stronger than the reference's JVM loop (no per-window allocation), so
    this is the LOWER bound on the true vs-Scala-UDF multiple; for exact
    configs its labels must agree with the per-row Python baseline
    exactly (``cpp_agreement``, enforced).
  * ``vs_cpp_mt`` / ``baseline_cpp_mt_docs_per_s`` — the same compiled
    scorer with ``os.cpu_count()`` threads: one TPU chip vs one whole
    multi-core host (the reference's transform is cluster-parallel by
    contract, so the single-thread number stands in for one executor core
    and this one for a whole executor host).
  * ``vs_baseline`` / ``baseline_docs_per_s`` — the same per-row
    semantics (per-window dict lookup + vector accumulate,
    LanguageDetectorModel.scala:139-152) in pure Python. Far slower than
    any JVM — the UPPER bound on the vs-Scala-UDF multiple.
  * ``vs_numpy`` / ``baseline_numpy_docs_per_s`` — the strongest
    vectorized CPU implementation this repo ships (numpy host scorer).

Each line also carries ``compute_docs_per_s``: device throughput with
operands already resident (no host->device wire), so kernel progress stays
visible when the tunnel's bandwidth — which bounds end-to-end ``value`` —
varies (the wire is a relay here, ~30-90MB/s bursty).

Accuracy parity is a hard gate per config: if device argmax labels disagree
with the per-row baseline on the comparison subset (>= 1000 docs or the
whole eval set), the script exits nonzero instead of reporting perf.

Environment knobs:
    BENCH_CONFIGS        comma list, default "2,3,4,5,1" (1 last = headline)
    BENCH_DOCS           override eval-doc count for every config
    BENCH_BASELINE_DOCS  override baseline/parity-doc count for every config
    BENCH_SOFT_BUDGET_S  soft wall-clock budget (default 900): once spent,
                         intermediate configs are skipped (noted on stderr)
                         so the final/headline config always runs; the
                         additive legs (accuracy legs, hashed-vs-exact)
                         skip first, when under ~2-4 min remain
    SLD_TPU_TESTS        "1" => also run the real-TPU parity suite
                         (tests/test_tpu_hw.py) after the headline config,
                         reporting to stderr (stdout stays parseable)
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

# ---------------------------------------------------------------- corpus ----
_LANG_CHARS = {
    "en": "the quick brown fox jumps over lazy dog and that is very nice ",
    "de": "der schnelle braune fuchs springt über den faulen hund schön ",
    "fr": "le renard brun rapide saute par dessus chien paresseux très ",
    "es": "el zorro marrón rápido salta sobre perro perezoso muy bien ",
    "it": "la volpe marrone veloce salta sopra il cane pigro molto bene ",
    "nl": "de snelle bruine vos springt over de luie hond erg mooi ",
    "pt": "a raposa marrom rápida pula sobre o cão preguiçoso muito bom ",
    "sv": "den snabba bruna räven hoppar över den lata hunden mycket fin ",
    "pl": "szybki brązowy lis przeskakuje nad leniwym psem bardzo ładnie ",
    "fi": "nopea ruskea kettu hyppää laiskan koiran yli erittäin mukava ",
}
_ALPHABET = "abcdefghijklmnopqrstuvwxyzäöüßéèêñçåøæšžčłćİığj"


def language_names(n: int) -> list[str]:
    """First ten real languages, then procedurally named synthetic ones."""
    real = list(_LANG_CHARS)
    return real[:n] if n <= len(real) else real + [
        f"l{i:03d}" for i in range(len(real), n)
    ]


def word_list(lang: str) -> list[str]:
    """Word inventory for a language: real list, or a procedurally generated
    vocabulary with a language-specific letter subset (so byte-n-gram
    profiles are separable the way natural orthographies are)."""
    if lang in _LANG_CHARS:
        return _LANG_CHARS[lang].split()
    # zlib.crc32 is stable across processes (hash() is salted per run, which
    # would make the synthetic corpora — and the bench numbers — drift).
    import zlib

    rng = np.random.default_rng(zlib.crc32(lang.encode()))
    letters = rng.choice(list(_ALPHABET), size=14, replace=False)
    return [
        "".join(rng.choice(letters, size=int(rng.integers(3, 9))))
        for _ in range(40)
    ]


def make_corpus(langs, n_docs, mean_len=1500, seed=0):
    """Synthetic Wikipedia-like docs: ~mean_len bytes of language-typical words."""
    rng = np.random.default_rng(seed)
    words = {l: word_list(l) for l in langs}
    docs, labels = [], []
    for i in range(n_docs):
        lang = langs[i % len(langs)]
        target = max(30, int(rng.normal(mean_len, mean_len / 4)))
        n_words = max(4, target // 7)
        docs.append(" ".join(rng.choice(words[lang], size=n_words)))
        labels.append(lang)
    return docs, labels


def make_mixed_corpus(lang_a, lang_b, n_docs, mean_len=400, frac_a=0.7, seed=11):
    """Code-switched docs: ``frac_a`` of the words from lang_a, the rest from
    lang_b. Ground truth = the dominant language (lang_a)."""
    rng = np.random.default_rng(seed)
    wa, wb = word_list(lang_a), word_list(lang_b)
    docs = []
    for _ in range(n_docs):
        n_words = max(6, int(rng.normal(mean_len, mean_len / 5)) // 7)
        mask = rng.random(n_words) < frac_a
        picks = np.where(mask, rng.choice(wa, n_words), rng.choice(wb, n_words))
        docs.append(" ".join(picks))
    return docs


# Confusable pairs for the harder accuracy legs, in preference order: the
# classic Romance/Germanic confusions when the config's language set has
# them, else the en/de fallback every config contains.
_CONFUSABLE_PAIRS = [("pt", "es"), ("nl", "de"), ("sv", "de"), ("en", "de")]


def accuracy_legs(model, cfg, langs):
    """Harder accuracy legs than the saturated 1.5KB corpus: short docs
    (tweet-length), confusable-language docs at short length, and a
    mixed-language (70/30 code-switched) dominant-label probe. The full-doc
    accuracy leg saturates at 1.0 on every config (the synthetic corpus
    separates cleanly at 1.5KB); these legs are where accuracy can regress.
    Ref metric: BASELINE 'accuracy parity vs CPU' — the reference's own
    accuracy is corpus-bound the same way (LanguageDetectorModel.scala:131-156
    has no length normalization, so short docs are its weak spot too)."""
    from spark_languagedetector_tpu import Table as _T

    col = model.get_output_col()

    def acc(docs, labels):
        out = model.transform(_T({"fulltext": docs}))
        return round(
            float(np.mean([a == b for a, b in zip(out.column(col), labels)])), 4
        )

    legs = {}
    # 2000 docs always: config 2's short-doc leg was established at 2000 in
    # round 3 — shrinking the sample would break round-over-round
    # comparability (and 2000 covers 176 languages at ~11 docs each).
    sd_docs, sd_labels = make_corpus(langs, 2000, mean_len=200, seed=9)
    legs["shortdoc_accuracy"] = acc(sd_docs, sd_labels)
    pairs = [p for p in _CONFUSABLE_PAIRS if p[0] in langs and p[1] in langs]
    if pairs:
        clangs = sorted({l for p in pairs for l in p})
        cd, cl = make_corpus(clangs, 600, mean_len=200, seed=10)
        legs["confusable_accuracy"] = acc(cd, cl)
        a, b = pairs[0]
        mixed = make_mixed_corpus(a, b, 300, mean_len=400, frac_a=0.7, seed=11)
        legs["mixed_dominant_accuracy"] = acc(mixed, [a] * len(mixed))
        legs["confusable_pair"] = f"{a}/{b}"
    return legs


# ------------------------------------------------- reference CPU baseline ----
def baseline_score(text: str, gram_map: dict, num_langs: int, gram_lengths):
    """Reference hot-loop semantics: per-window map lookup + accumulate."""
    data = text.encode("utf-8")
    acc = [0.0] * num_langs
    for n in gram_lengths:
        if len(data) >= n:
            for i in range(len(data) - n + 1):
                vec = gram_map.get(data[i : i + n])
                if vec is not None:
                    for j in range(num_langs):
                        acc[j] += vec[j]
        elif data:
            vec = gram_map.get(data)
            if vec is not None:
                for j in range(num_langs):
                    acc[j] += vec[j]
    return acc


def _bucket_map(model):
    """id → weight-list map for hashed/cuckoo profiles (per-row baseline)."""
    return {
        int(i): model.profile.weights[r].tolist()
        for r, i in enumerate(model.profile.ids)
    }


def baseline_score_ids(text: str, bucket_map: dict, spec, num_langs: int):
    data = text.encode("utf-8")
    acc = [0.0] * num_langs
    for n in spec.gram_lengths:
        if len(data) >= n:
            windows = (data[i : i + n] for i in range(len(data) - n + 1))
        elif data:
            windows = (data,)
        else:
            windows = ()
        for w in windows:
            vec = bucket_map.get(spec.gram_to_id(w))
            if vec is not None:
                for j in range(num_langs):
                    acc[j] += vec[j]
    return acc


def usable_cpus() -> int:
    """CPUs this process may actually run on — cgroup/taskset-aware, so the
    multi-thread denominator doesn't oversubscribe (and thus understate the
    host) in restricted environments."""
    try:
        return len(os.sched_getaffinity(0)) or 1
    except AttributeError:  # non-Linux
        return os.cpu_count() or 1


# ------------------------------------------------- compiled C++ baseline ----
def _cpp_key_vecs(model, cfg):
    """(keys, vecs) for the compiled reference-shape baseline's gram map.

    Exact profiles expose their string-keyed gram map directly
    (profile.gram_probabilities — the reference's Map[gram -> vector] form).
    Hashed profiles (config 5) have lossy bucket ids, so the map the
    reference would hold is reconstructed from the training corpus: every
    distinct training gram whose bucket survived top-k selection, weighted
    by its bucket's row (collided grams share a row, exactly as hashing
    merged them during fit).
    """
    prof = model.profile
    spec = prof.spec
    if spec.mode == "exact":
        gm = prof.gram_probabilities
        keys = list(gm)
        return keys, np.asarray([gm[k] for k in keys], dtype=np.float64)

    from spark_languagedetector_tpu import native
    from spark_languagedetector_tpu.ops.vocab import window_ids_numpy

    prof = prof.compacted()  # no-op unless the profile is the dense form
    langs = language_names(cfg["n_langs"])
    docs, _ = make_corpus(langs, cfg["train_per_lang"] * len(langs), seed=1)
    docs_b = [d.encode("utf-8") for d in docs]
    pad_to = max(len(d) for d in docs_b)
    batch, lengths = native.pack_batch(docs_b, pad_to)
    prof_ids = np.asarray(prof.ids, dtype=np.int64)
    keys: list[bytes] = []
    rows: list[np.ndarray] = []
    for n in spec.gram_lengths:
        ids = window_ids_numpy(batch, n, spec)
        W = ids.shape[1]
        valid = (np.arange(W)[None, :] + n) <= lengths[:, None]
        pos = np.searchsorted(prof_ids, ids)
        member = prof_ids[np.clip(pos, 0, len(prof_ids) - 1)] == ids
        b_idx, w_idx = np.nonzero(valid & member)
        if not b_idx.size:
            continue
        windows = np.lib.stride_tricks.sliding_window_view(batch, n, axis=1)[
            b_idx, w_idx
        ]
        uniq = np.unique(windows, axis=0)
        uids = window_ids_numpy(uniq, n, spec)[:, 0]
        urows = np.searchsorted(prof_ids, uids)
        keys.extend(u.tobytes() for u in uniq)
        rows.append(urows)
    rowsv = np.concatenate(rows) if rows else np.zeros(0, np.int64)
    return keys, np.asarray(prof.weights, dtype=np.float64)[rowsv]


def time_cpp_baseline(model, cfg, sub):
    """(docs/s single-thread, docs/s multi-thread, labels, map size) for the
    compiled baseline.

    Times the C++ scorer over the parity subset (best of >= 3 reps or 0.5s
    of wall clock, whichever is more) on one thread — the per-row-executor
    stand-in for the reference's JVM UDF hot loop — and once more with
    ``os.cpu_count()`` threads (``vs_cpp_mt``: the whole-host denominator,
    since the reference's transform is cluster-parallel by contract).
    Methodology note: best-of-reps favors the C++ side relative to the
    single-pass pure-Python denominator in time_baselines — the asymmetry
    DEFLATES vs_cpp (conservative for the device's claim), and is kept
    because the C++ pass is cheap enough to repeat while the Python pass
    costs minutes. Returns (None, None, None, None) when the native library
    is unavailable (bench still reports the Python denominators)."""
    try:
        from spark_languagedetector_tpu import native

        keys, vecs = _cpp_key_vecs(model, cfg)
        rs = native.RefScorer(keys, vecs)
    except Exception as e:  # measurement tool: degrade, don't kill the config
        print(
            json.dumps({"cpp_baseline_unavailable": f"{type(e).__name__}: {e}"}),
            file=sys.stderr,
            flush=True,
        )
        return None, None, None, None
    try:
        docs_b = [t.encode("utf-8") for t in sub]
        glens = model.profile.spec.gram_lengths
        labels = rs.score(docs_b, glens)

        def best_of(n_threads: int) -> float:
            best, reps, t_total = 0.0, 0, 0.0
            while (t_total < 0.5 or reps < 3) and reps < 10:
                t0 = time.perf_counter()
                rs.score(docs_b, glens, n_threads=n_threads)
                dt = time.perf_counter() - t0
                t_total += dt
                reps += 1
                best = max(best, len(docs_b) / dt)
            return best

        best = best_of(1)
        best_mt = best_of(usable_cpus())
        return best, best_mt, labels, len(keys)
    finally:
        rs.close()


def hashed_vs_exact(model, cfg, langs):
    """Collision cost of the 2^20 exact12 hashed vocab (config 5), measured
    against an EXACT n=1..5 model fitted on the same corpus with the same k
    (SURVEY §7.4: hashed mode changes accuracy and must be validated, not
    assumed). Reports label agreement on the full-length eval corpus plus
    the accuracy delta on the short-doc leg, where scarce signal makes
    collisions actually bite."""
    from spark_languagedetector_tpu import Table as _T

    try:
        exact_model = fit_model(dict(cfg, vocab="exact"))
        col = model.get_output_col()

        def labels_of(m, docs):
            return list(m.transform(_T({"fulltext": docs})).column(col))

        docs, truth = make_corpus(langs, 2000, seed=12)
        h, e = labels_of(model, docs), labels_of(exact_model, docs)
        agree = float(np.mean([a == b for a, b in zip(h, e)]))
        sdocs, struth = make_corpus(langs, 2000, mean_len=200, seed=13)
        hs, es = labels_of(model, sdocs), labels_of(exact_model, sdocs)
        acc_h = float(np.mean([a == b for a, b in zip(hs, struth)]))
        acc_e = float(np.mean([a == b for a, b in zip(es, struth)]))
        return {
            "hashed_vs_exact_agreement": round(agree, 4),
            "hashed_vs_exact_shortdoc_delta": round(acc_h - acc_e, 4),
            "exact_shortdoc_accuracy": round(acc_e, 4),
        }
    except Exception as e:  # diagnostic leg: degrade, don't kill the config
        print(
            json.dumps({"hashed_vs_exact_error": f"{type(e).__name__}: {e}"}),
            file=sys.stderr,
            flush=True,
        )
        return {}


# ------------------------------------------------------------ per config ----
CONFIGS = {
    1: dict(label="config1 bigram en/de/fr", n_langs=3, gram_lengths=[2],
            k=2000, vocab="exact", docs=20000, baseline_docs=1000,
            train_per_lang=60),
    2: dict(label="config2 n=1..3, 10 European languages", n_langs=10,
            gram_lengths=[1, 2, 3], k=3000, vocab="exact", docs=20000,
            baseline_docs=400, train_per_lang=60),
    3: dict(label="config3 n=1..5, 50 languages (CLD2-scale, exact/cuckoo)",
            n_langs=50, gram_lengths=[1, 2, 3, 4, 5], k=1000, vocab="exact",
            docs=8000, baseline_docs=120, train_per_lang=40),
    4: dict(label="config4 streaming micro-batch (10 languages, n=1..3)",
            n_langs=10, gram_lengths=[1, 2, 3], k=3000, vocab="exact",
            docs=10000, baseline_docs=200, train_per_lang=60, streaming=True),
    5: dict(label="config5 n=1..5 hashed 2^20, 176 languages (fastText-scale)",
            n_langs=176, gram_lengths=[1, 2, 3, 4, 5], k=400, vocab="hashed",
            docs=6000, baseline_docs=50, train_per_lang=30),
}

_model_cache: dict[tuple, object] = {}


def fit_model(cfg):
    from spark_languagedetector_tpu import LanguageDetector, Table

    key = (cfg["n_langs"], tuple(cfg["gram_lengths"]), cfg["k"], cfg["vocab"])
    if key in _model_cache:
        return _model_cache[key]
    langs = language_names(cfg["n_langs"])
    docs, labels = make_corpus(langs, cfg["train_per_lang"] * len(langs), seed=1)
    det = LanguageDetector(langs, cfg["gram_lengths"], cfg["k"]).set_vocab_mode(
        cfg["vocab"]
    ).set_hash_bits(20)
    model = det.fit(Table({"lang": labels, "fulltext": docs}))
    _model_cache[key] = model
    return model


def _baseline_scorer(model):
    """Per-row reference-semantics scorer closure for this model."""
    langs = model.profile.languages
    spec = model.profile.spec
    if spec.mode == "exact" and max(spec.gram_lengths) <= 3:
        gram_map = {g: list(v) for g, v in model.gram_probabilities.items()}
        return lambda t: baseline_score(
            t, gram_map, len(langs), spec.gram_lengths
        )
    bucket_map = _bucket_map(model)
    return lambda t: baseline_score_ids(t, bucket_map, spec, len(langs))


def compute_baseline_labels(model, cfg, eval_docs):
    """(per-row argmax labels, subset) — the parity gate's reference side.

    >= 1000 docs (or the whole eval set if smaller). This is the slow
    pure-Python part (~30-70s for the long-gram configs), so run_config
    overlaps it with the device warmup; only the LABELS are used from this
    pass — the timed denominators come from time_baselines, measured
    sequentially on an idle host.
    """
    n = int(
        os.environ.get(
            "BENCH_BASELINE_DOCS",
            max(cfg["baseline_docs"], min(1000, len(eval_docs))),
        )
    )
    if n <= 0:
        return None, [], None
    sub = eval_docs[:n]
    scorer = _baseline_scorer(model)
    return [int(np.argmax(scorer(t))) for t in sub], sub, scorer


def time_baselines(model, sub, scorer):
    """(per-row docs/s, numpy docs/s) measured sequentially (idle host).

    The per-row rate times a ~200-doc slice (stable enough; full-subset
    timing would re-pay the minutes the parity pass already spent), the
    numpy mirror times the whole subset (it is vectorized and cheap).
    ``scorer`` is the closure compute_baseline_labels already built (its
    gram/bucket tables are seconds of host work at vocab scale).
    """
    from spark_languagedetector_tpu.ops.score import score_batch_numpy

    if not sub:
        return None, None
    t_sub = sub[:200]
    t0 = time.perf_counter()
    for t in t_sub:
        scorer(t)
    t_base = time.perf_counter() - t0
    cw, cids = model.profile.host_arrays()
    spec = model.profile.spec
    t0 = time.perf_counter()
    score_batch_numpy([t.encode("utf-8") for t in sub], cw, cids, spec)
    t_np = time.perf_counter() - t0
    return len(t_sub) / t_base, len(sub) / t_np


_WIRE_SEQ = iter(range(1, 1 << 30))  # process-wide: probes never recur


def measure_wire_mbps():
    """h2d bandwidth probe: best-of-3 timed 4MB device_puts, RTT-corrected.

    Self-documents the relay's bandwidth weather in the artifact so a low
    end-to-end number can be read against the link, not the kernels (the
    tunneled wire swings 3-90MB/s across sessions with identical code).
    Each put is bounded by a scalar fetch; the fetch's round-trip is
    measured separately (a 1-byte put + the same fetch) and subtracted so
    a fast-but-high-RTT link is not misreported as slow.
    """
    import jax
    import jax.numpy as jnp

    try:
        rng = np.random.default_rng(0)
        # Every probe payload must be unique — including ACROSS calls (one
        # per config in the same process): the relay can serve a repeated
        # (executable, args) pair from cache (docs/PERFORMANCE.md §5), and
        # 1-byte random payloads collide with probability ~1/256 per pair.
        # The module-level counter stamps every buffer, so neither the RTT
        # probes nor the seeded 4MB payloads ever recur process-wide.

        def timed_put(nbytes):
            if nbytes <= 8:
                buf = np.frombuffer(
                    np.int64(next(_WIRE_SEQ)).tobytes(), np.uint8
                ).copy()
            else:
                buf = rng.integers(0, 256, (nbytes,), np.uint8)
                buf[:8] = np.frombuffer(
                    np.int64(next(_WIRE_SEQ)).tobytes(), np.uint8
                )
            t0 = time.perf_counter()
            dev = jax.device_put(buf)
            # A scalar reduce + fetch bounds the put's completion.
            float(jnp.sum(dev[:: 1 << 18].astype(jnp.int32)))
            return time.perf_counter() - t0

        timed_put(4 << 20)  # warm allocator + compile, discarded
        timed_put(8)  # warm the RTT probe's own (shape, executable), discarded
        rtt = min(timed_put(8) for _ in range(3))
        best = min(timed_put(4 << 20) for _ in range(3))
        if best - rtt <= 1e-3:
            # RTT swallowed the whole transfer window — any division here
            # reports an absurd rate; flag the measurement as unusable.
            return None
        return round((4 << 20) / (best - rtt) / 1e6, 1)
    except Exception:
        return None


def measure_compute_only(model, eval_docs):
    """Device docs/s with operands already resident — no host->device wire.

    Measures at exactly the production shape: ``batch_size`` rows (corpus
    tiled if shorter) at the eval docs' own length bucket, so the rate is
    directly comparable to ``value``. The relay can serve repeated
    identical (executable, args) executions from a cache
    (docs/PERFORMANCE.md §5), so every timed dispatch uses a buffer the
    relay has never executed: 13 row-rotations of the packed batch
    (identical compute cost, distinct contents), one spent on warmup and
    never timed, the rest dispatched exactly once each across 3 reps.
    """
    import jax

    from spark_languagedetector_tpu.ops.encoding import bucket_length

    runner = model._get_runner()
    if runner.mesh is not None:
        return None  # single-device measurement only
    docs_b = [t.encode("utf-8") for t in eval_docs]
    pad_to = bucket_length(max(len(d) for d in docs_b), runner.length_buckets)
    # Production row count: the runner's own bucket-cap policy, so the
    # timed shape is one the runner actually dispatches for this corpus's
    # length bucket.
    from spark_languagedetector_tpu.api.runner import rows_for_bucket

    rows = rows_for_bucket(pad_to, runner.batch_size)
    while len(docs_b) < rows:  # tile short corpora up to production size
        docs_b = docs_b + docs_b
    docs_b = [d[:pad_to] for d in docs_b[:rows]]
    batch_np, lengths_np = runner._pack(docs_b, pad_to)

    def rotation(g):
        # Tiling by doubling can leave the batch row-periodic (period <
        # 13), which would re-align some rotations into identical buffers
        # and re-enable the relay result cache; stamping the rotation index
        # into one byte makes every buffer distinct at identical compute
        # cost (same shapes, same op graph — only the timed value matters).
        rb = np.roll(batch_np, g, axis=0)
        rb[0, 0] = np.uint8(g + 1)
        return (
            jax.device_put(rb, runner.device),
            jax.device_put(np.roll(lengths_np, g), runner.device),
        )

    groups = [rotation(g) for g in range(13)]
    # Warm compile + first execution on the one rotation the loop never
    # times (its (args, executable) pair must not recur).
    wb, wl = groups[12]
    np.asarray(runner._dispatch_batch(wb, wl, None, runner.device))
    best_rate = 0.0
    for rep in range(3):
        t0 = time.perf_counter()
        acc = None
        for g in range(rep * 4, rep * 4 + 4):
            b, l = groups[g]
            s = runner._dispatch_batch(b, l, None, runner.device)
            acc = s.sum() if acc is None else acc + s.sum()
        float(np.asarray(acc))
        best_rate = max(best_rate, 4 * rows / (time.perf_counter() - t0))
    return best_rate


def run_config(num: int, deadline: float | None = None) -> dict:
    """One config's full measurement. ``deadline`` (perf_counter value) gates
    the ADDITIVE legs only — accuracy legs and the config-5 hashed-vs-exact
    comparison are skipped with a marker when the soft budget is nearly
    spent, so the core metrics (value + parity gate + denominators) always
    complete for every config the budget admits at all."""
    from concurrent.futures import ThreadPoolExecutor

    cfg = CONFIGS[num]
    model = fit_model(cfg)
    langs = language_names(cfg["n_langs"])
    n_docs = int(os.environ.get("BENCH_DOCS", cfg["docs"]))
    eval_docs, eval_labels = make_corpus(langs, n_docs, seed=2)
    eval_bytes = sum(len(d.encode()) for d in eval_docs)

    # The parity-label pass (~30-70s of pure-Python scoring at 1000 docs
    # for the long-gram configs) overlaps the device warmup: jit compiles
    # are remote-compile HTTP waits here, so the GIL is mostly free. Its
    # TIMING is never used — denominators come from time_baselines after
    # the join, sequentially, so neither side's measurement shares the
    # machine with the other.
    pool = ThreadPoolExecutor(max_workers=1)
    baseline_fut = pool.submit(compute_baseline_labels, model, cfg, eval_docs)
    try:

        if cfg.get("streaming"):
            from spark_languagedetector_tpu import Table
            from spark_languagedetector_tpu.stream.microbatch import (
                memory_source,
                run_stream,
            )

            rows = [{"fulltext": t} for t in eval_docs]
            sink_rows = []
            run_stream(  # warmup: compile every shape outside the timed window
                model, memory_source(rows, 8192), lambda t: None,
                prefetch=6, workers=4,
            )
            base_pred, sub, scorer = baseline_fut.result()
            baseline_dps, baseline_np_dps = time_baselines(model, sub, scorer)
            times = []
            # Streaming is transfer-bound like the other short-gram configs
            # and gets extra passes the same way (7 here: streaming passes
            # run the whole corpus through the engine, so they are slower
            # than the batch path's and one fewer keeps the budget).
            # Four transform workers with a deep prefetch
            # keep the bursty wire saturated across batches (A/B on the
            # tunneled v5e: w2/p3 11.3k, w4/p6 24.9-25.2k rows/s in the same
            # window; w6+/deeper plateaus). 8192-row source batches beat 4096
            # consistently (fewer transform calls, deeper in-call pipelining;
            # 19.9k vs 13.7k rows/s on a cold wire, ~5% ahead when warm).
            for _ in range(7 if max(cfg["gram_lengths"]) <= 3 else 3):
                t0 = time.perf_counter()
                q = run_stream(
                    model, memory_source(rows, 8192), sink_rows.append,
                    prefetch=6, workers=4,
                )
                times.append(time.perf_counter() - t0)
                sink_rows.clear()
            t_dev = min(times)
            device_dps = n_docs / t_dev
            median_dps = n_docs / sorted(times)[len(times) // 2]
            # Parity gate for the streaming path: labels produced by the same
            # model.transform the engine drives, compared row-for-row against
            # the per-row baseline's argmax.
            parity = None
            if base_pred:
                out = model.transform(Table({"fulltext": list(sub)}))
                dev_labels = list(out.column(model.get_output_col()))
                parity = float(
                    np.mean([langs[p] == d for p, d in zip(base_pred, dev_labels)])
                )
            full = model.transform(Table({"fulltext": eval_docs}))
            accuracy = float(np.mean([
                a == b
                for a, b in zip(full.column(model.get_output_col()), eval_labels)
            ]))
        else:
            from spark_languagedetector_tpu.ops.encoding import texts_to_bytes

            runner = model._get_runner()
            docs_b = texts_to_bytes(eval_docs)
            # Warmup = one full pass, so every (batch, length-bucket) shape XLA
            # will see — including the ragged final batch — is compiled outside
            # the timed window. The timed pass is the LABEL pipeline (device
            # argmax, int32 ids fetched) — what the reference's transform
            # produces; score fetches of [N, L] floats would bill d2h wire the
            # product never pays.
            ids = runner.predict_ids(docs_b)
            base_pred, sub, scorer = baseline_fut.result()
            baseline_dps, baseline_np_dps = time_baselines(model, sub, scorer)
            # Best of N timed passes: the device link (e.g. a tunneled TPU) has
            # bursty latency/bandwidth that can dominate a single pass; the best
            # pass is the closest observable to steady-state throughput. The
            # median is reported alongside so the burst variance is visible.
            # Transfer-bound configs (short gram lengths ⇒ compute hides
            # under the wire) get extra passes: each is ~0.5-1.5s and the
            # relay's stall windows last seconds, so more samples raise the
            # odds that min-time lands in clear weather.
            n_passes = 8 if max(cfg["gram_lengths"]) <= 3 else 4
            pass_times = []
            for _ in range(n_passes):
                t0 = time.perf_counter()
                ids = runner.predict_ids(docs_b)
                pass_times.append(time.perf_counter() - t0)
            t_dev = min(pass_times)
            device_dps = n_docs / t_dev
            median_dps = n_docs / sorted(pass_times)[len(pass_times) // 2]
            parity = None
            if base_pred:
                dev_pred = ids[: len(sub)].tolist()
                parity = float(np.mean([a == b for a, b in zip(base_pred, dev_pred)]))
            accuracy = float(np.mean(
                [langs[i] == want for i, want in zip(ids, eval_labels)]
            ))

        if parity is not None and parity < 1.0:
            raise SystemExit(
                f"accuracy parity violated on {cfg['label']}: {parity:.4f} — "
                "device argmax disagrees with the reference-semantics baseline; "
                "refusing to report perf"
            )

        import jax

        # Compiled reference-shape baseline (vs_cpp): timed after the device
        # passes so the host is idle. For exact configs the C++ map is the
        # model's own gram map, so its labels must agree with the per-row
        # Python baseline exactly (same map, same accumulation order, both
        # in double) — reported as cpp_agreement and ENFORCED below: a
        # semantics drift in refscorer.cpp would silently skew the headline
        # vs_cpp denominator.
        cpp_dps, cpp_mt_dps, cpp_labels, cpp_map_grams = (
            time_cpp_baseline(model, cfg, sub)
            if sub
            else (None, None, None, None)
        )
        cpp_agree = None
        if cpp_labels is not None and base_pred:
            cpp_agree = float(np.mean(
                [a == b for a, b in zip(base_pred, cpp_labels.tolist())]
            ))
            if cpp_agree < 1.0 and model.profile.spec.mode == "exact":
                raise SystemExit(
                    f"C++ baseline disagreement on {cfg['label']}: "
                    f"{cpp_agree:.4f} — refscorer.cpp has drifted from the "
                    "per-row reference semantics; the vs_cpp denominator "
                    "would be wrong, refusing to report perf"
                )
        compute_dps = measure_compute_only(model, eval_docs)
        wire_mbps = measure_wire_mbps()
        result = {
            "metric": f"langid docs/sec/chip ({cfg['label']}, {jax.default_backend()})",
            "value": round(device_dps, 1),
            "unit": "docs/sec",
            "config": num,
            "median_docs_per_s": round(median_dps, 1),
            "baseline_kind": "python-per-row (reference hot-loop semantics)",
            "argmax_parity": parity,
            # Ground-truth label accuracy on the synthetic eval corpus —
            # the BASELINE metric's accuracy leg (parity above pins
            # equivalence to the reference semantics; this pins that both
            # actually detect the right language).
            "accuracy": round(accuracy, 4),
            "parity_docs": len(sub),
            "eval_docs": n_docs,
            "eval_mb": round(eval_bytes / 1e6, 1),
        }
        if wire_mbps is not None:
            result["wire_mbps"] = wire_mbps
        if compute_dps:
            # Conservative kernel rate: full-width docs (truncated to the widest
            # bucket), resident operands. End-to-end `value` can exceed it when
            # the real corpus is shorter than the bucket width.
            result["compute_docs_per_s"] = round(compute_dps, 1)
        if not cfg.get("streaming"):
            result["strategy"] = model._get_runner().strategy
        def budget_left(need_s: float) -> bool:
            return deadline is None or time.perf_counter() + need_s < deadline

        # Additive legs (new shapes compile ~20-40s each through a remote-
        # compile tunnel): only when the soft budget has room, so a driver
        # on the default budget still gets every config's core metrics.
        if budget_left(120):
            result.update(accuracy_legs(model, cfg, langs))
        else:
            result["accuracy_legs"] = "skipped (soft budget)"
        if num == 5:
            if budget_left(240):
                result.update(hashed_vs_exact(model, cfg, langs))
            else:
                result["hashed_vs_exact"] = "skipped (soft budget)"
        if baseline_dps:
            result["vs_baseline"] = round(device_dps / baseline_dps, 2)
            result["vs_numpy"] = round(device_dps / baseline_np_dps, 2)
            result["baseline_docs_per_s"] = round(baseline_dps, 1)
            result["baseline_numpy_docs_per_s"] = round(baseline_np_dps, 1)
        if cpp_dps:
            result["vs_cpp"] = round(device_dps / cpp_dps, 2)
            result["baseline_cpp_docs_per_s"] = round(cpp_dps, 1)
            result["cpp_map_grams"] = cpp_map_grams
            if cpp_agree is not None:
                result["cpp_agreement"] = round(cpp_agree, 4)
        if cpp_mt_dps:
            result["vs_cpp_mt"] = round(device_dps / cpp_mt_dps, 2)
            result["baseline_cpp_mt_docs_per_s"] = round(cpp_mt_dps, 1)
            result["cpp_threads"] = usable_cpus()
        if cfg.get("streaming"):
            result["note"] = "rows/sec through run_stream incl. sink"
        return result
    finally:
        # Always reap the baseline thread — an exception during warmup
        # must not leave a GIL-grinding scorer polluting the next
        # config's timed measurements.
        pool.shutdown(wait=True)


def main():
    order = [
        int(c)
        for c in os.environ.get("BENCH_CONFIGS", "2,3,4,5,1").split(",")
        if c.strip()
    ]
    # Soft wall-clock budget: a full five-config run is dominated by one-off
    # jit compiles (~6 min through a remote-compile tunnel). If a driver
    # enforces a timeout, the headline config (last in the list) must still
    # run — so once the budget is spent, intermediate configs are skipped
    # (noted on stderr) and the run jumps straight to the final config.
    budget_s = float(os.environ.get("BENCH_SOFT_BUDGET_S", "900"))
    t_start = time.perf_counter()
    deadline = t_start + budget_s
    failures = 0
    summary: dict[int, dict] = {}
    for i, num in enumerate(order):
        last = i == len(order) - 1
        if not last and time.perf_counter() - t_start > budget_s:
            print(
                json.dumps({"config": num, "skipped": "soft time budget"}),
                file=sys.stderr,
                flush=True,
            )
            summary[num] = {"skipped": "soft time budget"}
            continue
        try:
            result = run_config(num, deadline=deadline)
            print(json.dumps(result), flush=True)
            summary[num] = {
                k: result[k]
                for k in (
                    "value", "vs_baseline", "vs_numpy", "vs_cpp", "vs_cpp_mt",
                    "argmax_parity", "accuracy", "shortdoc_accuracy",
                    "confusable_accuracy", "mixed_dominant_accuracy",
                    "hashed_vs_exact_agreement",
                    "hashed_vs_exact_shortdoc_delta",
                    "compute_docs_per_s", "wire_mbps",
                )
                if k in result
            }
        except SystemExit:
            raise
        except Exception as e:  # keep later configs (incl. headline) alive
            failures += 1
            print(
                json.dumps(
                    {"config": num, "error": f"{type(e).__name__}: {e}"}
                ),
                file=sys.stderr,
                flush=True,
            )
            summary[num] = {"error": f"{type(e).__name__}: {e}"}
    # The driver stores only the stdout TAIL; per-config lines can be
    # truncated off the top (config 2 was lost from BENCH_r03.json). This
    # final compact line repeats every config's key numbers so the most
    # size-limited artifact in the loop survives a 4KB cut. It mirrors the
    # headline config's metric/value/unit at top level so a driver that
    # parses only the last stdout line still reads the headline number.
    # Printed BEFORE the hardware suite: that suite takes minutes and
    # reports to stderr only, so a driver timeout during it must not cost
    # the summary (it stays the last stdout line either way).
    final = dict(summary.get(order[-1], {})) if order else {}
    final.setdefault("metric", "langid docs/sec/chip (headline, config "
                     f"{order[-1] if order else '?'})")
    final.setdefault("unit", "docs/sec")
    final["summary"] = summary
    print(json.dumps(final, separators=(",", ":")), flush=True)
    remaining = budget_s - (time.perf_counter() - t_start)
    run_tpu_hw_tests(remaining)
    if failures:
        sys.exit(1)


def run_tpu_hw_tests(remaining_budget_s: float = 300.0):
    """Opt-in real-hardware Mosaic parity suite, after the headline config.

    Runs with SLD_TPU_TESTS=1 so the opt-in tests in tests/test_tpu_hw.py
    execute on the actual chip once per bench run. Reports to STDERR only —
    stdout's last line must stay the headline config's JSON (drivers
    tail-parse it) — and a hung tunnel is bounded by a subprocess timeout.

    The suite runs in a subprocess, which needs a device stack that admits a
    second client while this process holds the chip (true of the axon relay
    here). On a co-located single-client libtpu, run the suite standalone
    instead: SLD_TPU_TESTS=1 pytest tests/test_tpu_hw.py.

    Default policy: opportunistic — the suite runs whenever the bench just
    completed on a healthy chip AND enough soft budget remains (>= 60s);
    SLD_TPU_TESTS=1 forces it, SLD_TPU_TESTS=0 disables it.
    """
    flag = os.environ.get("SLD_TPU_TESTS", "")
    if flag == "0":
        return
    if flag != "1" and remaining_budget_s < 60:
        return
    import subprocess

    # The suite is 7 tests now (mesh + hist/hybrid e2e added round 4) and a
    # cold run costs ~4-6 min of remote-tunnel compiles; 300s truncated the
    # whole suite to "timeout" with zero partial results.
    timeout_s = float(os.environ.get("SLD_TPU_TESTS_TIMEOUT_S", "0")) or (
        720.0 if flag == "1" else max(60.0, min(600.0, remaining_budget_s))
    )
    here = os.path.dirname(os.path.abspath(__file__))
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "pytest", "tests/test_tpu_hw.py", "-q"],
            cwd=here,
            env={**os.environ, "SLD_TPU_TESTS": "1"},
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
        tail = (proc.stdout or "").strip().splitlines()[-1:]
        print(
            json.dumps(
                {
                    "tpu_hw_tests": "passed" if proc.returncode == 0 else "FAILED",
                    "detail": tail[0] if tail else "",
                }
            ),
            file=sys.stderr,
            flush=True,
        )
    except subprocess.TimeoutExpired:
        print(
            json.dumps({"tpu_hw_tests": "timeout"}), file=sys.stderr, flush=True
        )


if __name__ == "__main__":
    main()
